"""Serving counters: the numbers that tell you whether the server is keeping up.

The reference lineage has no serving tier to observe; the inference
stacks this subsystem borrows its shape from (continuous-batching LLM
servers, Podracer actor pools) live and die by a small set of gauges —
queue depth, lane occupancy, admit/retire/timeout rates, retraces — so
the serve layer carries the same set from day one. Everything here is
host-side Python (incremented by the scheduler loop between device
dispatches); nothing touches the jitted window program.

``ServerMetrics.snapshot()`` is the one read surface: the CLI summary,
the ``server_meta.json`` sidecar, tests, and ``bench_serve.py`` all
consume it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Mapping, Optional


def percentiles(samples: List[float], points=(50.0, 95.0, 99.0)) -> Dict[str, Optional[float]]:
    """{"p50": ..., "p95": ..., "p99": ...} by linear interpolation —
    tiny and dependency-free so metrics never import numpy for three
    numbers. Empty input yields ``None`` entries (a server that served
    nothing has no latency, not a zero latency)."""
    out: Dict[str, Optional[float]] = {}
    ordered = sorted(samples)
    for p in points:
        key = f"p{p:g}"
        if not ordered:
            out[key] = None
            continue
        rank = (len(ordered) - 1) * (p / 100.0)
        lo = int(rank)
        hi = min(lo + 1, len(ordered) - 1)
        out[key] = ordered[lo] + (ordered[hi] - ordered[lo]) * (rank - lo)
    return out


class ServerMetrics:
    """Counters + gauges + latency samples for one ``SimServer``.

    Counter semantics (all monotonic over the server's lifetime):

    - ``submitted``/``rejected``: every ``submit`` call lands in exactly
      one of these (rejected = bounded-queue backpressure).
    - ``admitted``: requests scattered into a lane.
    - ``retired``: horizons that ran to completion.
    - ``resubmitted``: continuation tickets created by
      ``SimServer.resubmit`` (a held DONE request extended past its
      horizon — the sweep driver's rung promotions).
    - ``timeouts``: deadline expiries (queued or mid-run).
    - ``cancelled``: explicit cancels (queued or mid-run).
    - ``failed``: admission-time construction errors (bad overrides).
    - ``ticks``: scheduler iterations; ``windows``: device window
      programs actually dispatched (a tick with no occupied lanes runs
      no window).
    - ``lane_windows_busy`` / ``lane_windows_total``: per-window lane
      occupancy accumulators — their ratio is the mean occupancy, the
      serving analogue of duty cycle.
    - ``retraces``: compiled-program count of the window executable
      beyond the expected single trace; anything nonzero means a shape
      leaked into the hot loop.
    """

    _COUNTERS = (
        "submitted",
        "rejected",
        "admitted",
        "retired",
        "resubmitted",
        "timeouts",
        "cancelled",
        "failed",
        "ticks",
        "windows",
        "lane_windows_busy",
        "lane_windows_total",
    )

    def __init__(self) -> None:
        self.counters: Dict[str, int] = {k: 0 for k in self._COUNTERS}
        self.queue_depth = 0
        self.lanes_busy = 0
        self.lanes_total = 0
        self.retraces = 0
        self._t0 = time.perf_counter()
        # per finished request: wall seconds submit->admit and submit->done
        self.wait_seconds: List[float] = []
        self.latency_seconds: List[float] = []
        self.window_seconds: List[float] = []

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def observe_request(self, wait_s: float, total_s: float) -> None:
        self.wait_seconds.append(float(wait_s))
        self.latency_seconds.append(float(total_s))

    def observe_window(self, wall_s: float) -> None:
        self.window_seconds.append(float(wall_s))

    def avg_window_seconds(self, default: float = 0.1) -> float:
        """Recent mean window wall time — the unit the backpressure
        retry-after hint is quoted in. Falls back to ``default`` before
        the first window has run (cold server, nothing measured)."""
        recent = self.window_seconds[-32:]
        return sum(recent) / len(recent) if recent else default

    def occupancy(self) -> Optional[float]:
        total = self.counters["lane_windows_total"]
        if total == 0:
            return None
        return self.counters["lane_windows_busy"] / total

    def snapshot(self) -> Dict[str, Any]:
        return {
            "counters": dict(self.counters),
            "queue_depth": self.queue_depth,
            "lanes_busy": self.lanes_busy,
            "lanes_total": self.lanes_total,
            "occupancy": self.occupancy(),
            "retraces": self.retraces,
            "uptime_seconds": time.perf_counter() - self._t0,
            "avg_window_seconds": (
                self.avg_window_seconds() if self.window_seconds else None
            ),
            "latency_seconds": percentiles(self.latency_seconds),
            "wait_seconds": percentiles(self.wait_seconds),
        }


def write_server_meta(
    out_dir: str, config: Mapping[str, Any], metrics: ServerMetrics
) -> str:
    """The ``server_meta.json`` sidecar: serving config + final counter
    snapshot, beside the per-request result logs — the serve analogue of
    the run path's ``colony_meta.json`` (provenance that is not
    recoverable from the data files themselves)."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "server_meta.json")
    payload = {"config": dict(config), **metrics.snapshot()}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, default=str)
    os.replace(tmp, path)
    return path
