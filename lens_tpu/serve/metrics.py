"""Serving counters: the numbers that tell you whether the server is keeping up.

The reference lineage has no serving tier to observe; the inference
stacks this subsystem borrows its shape from (continuous-batching LLM
servers, Podracer actor pools) live and die by a small set of gauges —
queue depth, lane occupancy, admit/retire/timeout rates, retraces — so
the serve layer carries the same set from day one. Everything here is
host-side Python (incremented by the scheduler loop between device
dispatches); nothing touches the jitted window program.

``ServerMetrics.snapshot()`` is the one read surface: the CLI summary,
the ``server_meta.json`` sidecar, tests, and ``bench_serve.py`` all
consume it.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple


def percentiles(samples: List[float], points=(50.0, 95.0, 99.0)) -> Dict[str, Optional[float]]:
    """{"p50": ..., "p95": ..., "p99": ...} by linear interpolation —
    tiny and dependency-free so metrics never import numpy for three
    numbers. Empty input yields ``None`` entries (a server that served
    nothing has no latency, not a zero latency)."""
    out: Dict[str, Optional[float]] = {}
    ordered = sorted(samples)
    for p in points:
        key = f"p{p:g}"
        if not ordered:
            out[key] = None
            continue
        rank = (len(ordered) - 1) * (p / 100.0)
        lo = int(rank)
        hi = min(lo + 1, len(ordered) - 1)
        out[key] = ordered[lo] + (ordered[hi] - ordered[lo]) * (rank - lo)
    return out


class ServerMetrics:
    """Counters + gauges + latency samples for one ``SimServer``.

    Counter semantics (all monotonic over the server's lifetime):

    - ``submitted``/``rejected``: every ``submit`` call lands in exactly
      one of these (rejected = bounded-queue backpressure).
    - ``admitted``: requests scattered into a lane.
    - ``retired``: horizons that ran to completion.
    - ``resubmitted``: continuation tickets created by
      ``SimServer.resubmit`` (a held DONE request extended past its
      horizon — the sweep driver's rung promotions).
    - ``timeouts``: deadline expiries (queued or mid-run).
    - ``cancelled``: explicit cancels (queued or mid-run).
    - ``failed``: admission-time construction errors (bad overrides).
    - ``ticks``: scheduler iterations; ``windows``: device window
      programs actually dispatched (a tick with no occupied lanes runs
      no window).
    - ``lane_windows_busy`` / ``lane_windows_total``: per-window lane
      occupancy accumulators — their ratio is the mean occupancy, the
      serving analogue of duty cycle.
    - ``retraces``: compiled-program count of the window executable
      beyond the expected single trace; anything nonzero means a shape
      leaked into the hot loop.
    - prefix cache (round 11, docs/serving.md "Prefix caching &
      forking"): ``prefix_hits``/``prefix_misses`` — resolution of each
      prefix-declaring submit against the snapshot store (a miss
      launches one internal prefix run); ``prefix_coalesced`` —
      submits that attached to an ALREADY in-flight prefix run instead
      of launching their own; ``prefix_forks`` — lanes seeded by
      scattering a cached/shared snapshot (every prefixed admission);
      ``snapshot_evictions`` — store entries dropped to the byte
      budget. ``admitted``/``retired`` include the internal prefix
      tickets (they really occupy lanes); ``submitted`` counts client
      submits only.
    - fault tolerance (round 12, docs/serving.md "Fault tolerance &
      recovery"): ``diverged`` — lanes the per-window finite check
      quarantined (each also counts under ``failed``); ``recovered`` —
      unfinished requests re-admitted from the WAL at
      ``recover_dir`` startup.
    - mesh failover (round 13, docs/serving.md "Mesh serving & device
      failover"): ``requeued`` — client requests displaced from a
      quarantined DEVICE and re-queued onto surviving shards under
      their original ids. The per-shard view lives in the ``shards``
      gauge list (occupancy, windows, diverged, snapshot bytes,
      quarantined flag per device) plus the ``quarantined_devices``
      count — both refreshed by the server alongside queue depth.
    """

    _COUNTERS = (
        "submitted",
        "rejected",
        "admitted",
        "retired",
        "resubmitted",
        "timeouts",
        "cancelled",
        "failed",
        "ticks",
        "windows",
        "lane_windows_busy",
        "lane_windows_total",
        "prefix_hits",
        "prefix_misses",
        "prefix_coalesced",
        "prefix_forks",
        "snapshot_evictions",
        "diverged",
        "recovered",
        "requeued",
    )

    def __init__(self) -> None:
        self.counters: Dict[str, int] = {k: 0 for k in self._COUNTERS}
        self.queue_depth = 0
        self.lanes_busy = 0
        self.lanes_total = 0
        self.retraces = 0
        # snapshot-store gauges (refreshed by the server alongside
        # queue depth / busy lanes)
        self.snapshots_resident = 0
        self.snapshot_bytes = 0
        # mesh gauges: one dict per device shard (index, device,
        # quarantined, lanes, occupancy, windows, diverged,
        # snapshot_bytes) + the quarantined-device count
        self.shards: List[Dict[str, Any]] = []
        self.quarantined_devices = 0
        self._t0 = time.perf_counter()
        # per finished request: wall seconds submit->admit and submit->done
        self.wait_seconds: List[float] = []
        self.latency_seconds: List[float] = []
        self.window_seconds: List[float] = []
        # per streamed window: (dispatched_at, ready_at, streamed_at) —
        # dispatch is when the scheduler enqueued the window program,
        # ready is when its trajectory finished landing host-side, and
        # streamed is when the last sink append for it returned. The
        # pipeline gauges below (device busy fraction, host gap,
        # stream lag) are all derived from these three timestamps.
        self.stream_samples: List[Tuple[float, float, float]] = []
        # scheduler seconds blocked on streamer backpressure (the
        # bounded queue full — host streaming is the bottleneck)
        self.stall_seconds = 0.0
        self.stalls = 0

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def observe_request(self, wait_s: float, total_s: float) -> None:
        self.wait_seconds.append(float(wait_s))
        self.latency_seconds.append(float(total_s))

    def observe_window(self, wall_s: float) -> None:
        self.window_seconds.append(float(wall_s))

    def observe_stream(
        self, dispatched_at: float, ready_at: float, streamed_at: float
    ) -> None:
        self.stream_samples.append(
            (float(dispatched_at), float(ready_at), float(streamed_at))
        )

    def observe_stall(self, seconds: float) -> None:
        if seconds > 0:
            self.stall_seconds += float(seconds)
            self.stalls += 1

    def device_busy_fraction(self) -> Optional[float]:
        """Fraction of the streamed span the device had a window in
        flight: per window, busy time runs from max(its dispatch, the
        previous window's ready) to its ready — windows queue behind
        each other on the device, so the previous ready is when this
        one's compute could start. An approximation (ready includes
        the host transfer tail), but it moves the right way: 1.0 means
        the device never waited for the host; the r08 synchronous path
        idled the device for the whole slice/append/flush stretch of
        every window."""
        samples = sorted(self.stream_samples)
        if not samples:
            return None
        span = max(s[2] for s in samples) - samples[0][0]
        if span <= 0:
            return None
        busy = 0.0
        prev_ready = None
        for dispatched, ready, _ in samples:
            start = dispatched if prev_ready is None else max(
                dispatched, prev_ready
            )
            busy += max(ready - start, 0.0)
            prev_ready = ready
        return min(busy / span, 1.0)

    def host_gap_seconds(self) -> List[float]:
        """Per-window host streaming time (ready -> last append)."""
        return [s[2] - s[1] for s in self.stream_samples]

    def stream_lag_seconds(self) -> List[float]:
        """Per-window dispatch -> fully-streamed latency: how far
        behind the device the sinks run (a tailing reader's staleness
        bound)."""
        return [s[2] - s[0] for s in self.stream_samples]

    def avg_window_seconds(self, default: float = 0.1) -> float:
        """Recent mean window wall time — the unit the backpressure
        retry-after hint is quoted in. Falls back to ``default`` before
        the first window has run (cold server, nothing measured)."""
        recent = self.window_seconds[-32:]
        return sum(recent) / len(recent) if recent else default

    def occupancy(self) -> Optional[float]:
        total = self.counters["lane_windows_total"]
        if total == 0:
            return None
        return self.counters["lane_windows_busy"] / total

    def snapshot(self) -> Dict[str, Any]:
        return {
            "counters": dict(self.counters),
            "queue_depth": self.queue_depth,
            "lanes_busy": self.lanes_busy,
            "lanes_total": self.lanes_total,
            "occupancy": self.occupancy(),
            "retraces": self.retraces,
            "snapshots_resident": self.snapshots_resident,
            "snapshot_bytes": self.snapshot_bytes,
            "shards": [dict(s) for s in self.shards],
            "quarantined_devices": self.quarantined_devices,
            "uptime_seconds": time.perf_counter() - self._t0,
            "avg_window_seconds": (
                self.avg_window_seconds() if self.window_seconds else None
            ),
            "latency_seconds": percentiles(self.latency_seconds),
            "wait_seconds": percentiles(self.wait_seconds),
            "device_busy_fraction": self.device_busy_fraction(),
            "host_gap_seconds": percentiles(self.host_gap_seconds()),
            "stream_lag_seconds": percentiles(self.stream_lag_seconds()),
            "stream_stall_seconds": self.stall_seconds,
            "stream_stalls": self.stalls,
        }


def write_server_meta(
    out_dir: str, config: Mapping[str, Any], metrics: ServerMetrics
) -> str:
    """The ``server_meta.json`` sidecar: serving config + final counter
    snapshot, beside the per-request result logs — the serve analogue of
    the run path's ``colony_meta.json`` (provenance that is not
    recoverable from the data files themselves)."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "server_meta.json")
    payload = {"config": dict(config), **metrics.snapshot()}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, default=str)
    os.replace(tmp, path)
    return path
