"""Serving metrics: the numbers that tell you whether the server is keeping up.

The reference lineage has no serving tier to observe; the inference
stacks this subsystem borrows its shape from (continuous-batching LLM
servers, Podracer actor pools) live and die by a small set of gauges —
queue depth, lane occupancy, admit/retire/timeout rates, retraces — so
the serve layer carries the same set from day one. Everything here is
host-side Python (incremented by the scheduler loop between device
dispatches); nothing touches the jitted window program.

Since round 14 the internals are a real instrument registry
(:class:`lens_tpu.obs.metrics.MetricsRegistry`) instead of bare ints
and lists, which buys three things the snapshot-only form could not:

- **time series** — ``sample_point()`` renders one compact record per
  wall-clock sampling tick; the server appends them to a
  ``metrics.jsonl`` ring (``metrics_interval_s``), so occupancy, queue
  depth, stream lag, and per-shard health exist as HISTORY, not just a
  final number;
- **pull exposition** — ``prometheus_text()`` renders the standard
  Prometheus text format for a scraper (the ``status()``-style pull
  surface: no push loop, no daemon — the caller asks);
- **thread safety** — latency/wait/window samples live in locked
  histograms, fixing the ``reset_samples()``-vs-concurrent-``tick()``
  race (the stream thread observes a completion while a bench warmup
  resets: the old list could be read half-cleared mid-percentile).

``ServerMetrics.snapshot()`` remains the one JSON read surface: the CLI
summary, the ``server_meta.json`` sidecar, tests, and ``bench_serve.py``
all consume it, with the same keys as before the refactor.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Tuple

from lens_tpu.obs.metrics import MetricsRegistry, percentiles

__all__ = ["ServerMetrics", "percentiles", "write_server_meta"]

#: help strings for the exported counters (the docstring below is the
#: narrative; this is what a scraper's HELP line shows)
_COUNTER_HELP = {
    "submitted": "client submits accepted into the queue",
    "rejected": "submits refused by bounded-queue backpressure",
    "admitted": "requests scattered into a lane",
    "retired": "horizons run to completion",
    "resubmitted": "continuation tickets from SimServer.resubmit",
    "timeouts": "deadline expiries (queued or mid-run)",
    "cancelled": "explicit cancels (queued or mid-run)",
    "failed": "requests failed (admission errors, divergence, faults)",
    "ticks": "scheduler iterations",
    "windows": "device window programs dispatched",
    "lane_windows_busy": "occupied lane-windows (occupancy numerator)",
    "lane_windows_total": "total lane-windows (occupancy denominator)",
    "prefix_hits": "prefix submits resolved from the snapshot store",
    "prefix_misses": "prefix submits that launched a prefix run",
    "prefix_coalesced": "prefix submits attached to an in-flight run",
    "prefix_forks": "lanes seeded by scattering a cached snapshot",
    "snapshot_evictions": "snapshot-store entries dropped to budget",
    "snapshot_rejected": "snapshot puts not retained (over budget)",
    "warm_submitted": "speculative prefix warm runs launched",
    "warm_completed": "warm runs that published their snapshot",
    "warm_hits": "prefix submits served by speculative warming",
    "warm_preempted": "warm lanes preempted for client admissions",
    "diverged": "lanes quarantined by the per-window finite check",
    "recovered": "unfinished WAL requests re-admitted at startup",
    "requeued": "requests displaced from a quarantined device",
    "stolen": "queued requests withdrawn by the cluster router",
    "adopted": "displaced requests adopted from another host's WAL",
    "hosts_down": "cluster hosts declared down by the router",
    "sink_failed": "requests failed by a request-scoped sink error",
    "result_hits": "submits served whole from the result cache",
    "result_misses": "fingerprinted submits the result cache lacked",
    "result_evictions": "result-cache entries dropped to budget",
    "suffix_coalesced":
        "submits coalesced onto an identical in-flight request",
    "device_seconds_saved":
        "estimated device-window seconds not spent thanks to result-"
        "cache hits and suffix dedup (windows avoided x mean window "
        "wall seconds)",
}

#: Per-tenant counter names (round 15, docs/serving.md "Front door"):
#: ``admitted``/``rejected`` are incremented by the server at submit
#: (accepted into the queue / bounded-queue backpressure) for any
#: request carrying a ``tenant``; ``throttled`` (rate-limit and
#: in-flight-quota refusals) and ``streamed_bytes`` (record bytes
#: streamed to the tenant over HTTP) are incremented by the front
#: door, which owns those policies.
TENANT_COUNTERS = ("admitted", "rejected", "throttled", "streamed_bytes")


class ServerMetrics:
    """Counters + gauges + latency samples for one ``SimServer``.

    Counter semantics (all monotonic over the server's lifetime):

    - ``submitted``/``rejected``: every ``submit`` call lands in exactly
      one of these (rejected = bounded-queue backpressure).
    - ``admitted``: requests scattered into a lane.
    - ``retired``: horizons that ran to completion.
    - ``resubmitted``: continuation tickets created by
      ``SimServer.resubmit`` (a held DONE request extended past its
      horizon — the sweep driver's rung promotions).
    - ``timeouts``: deadline expiries (queued or mid-run).
    - ``cancelled``: explicit cancels (queued or mid-run).
    - ``failed``: admission-time construction errors (bad overrides).
    - ``ticks``: scheduler iterations; ``windows``: device window
      programs actually dispatched (a tick with no occupied lanes runs
      no window).
    - ``lane_windows_busy`` / ``lane_windows_total``: per-window lane
      occupancy accumulators — their ratio is the mean occupancy, the
      serving analogue of duty cycle.
    - ``retraces``: compiled-program count of the window executable
      beyond the expected single trace; anything nonzero means a shape
      leaked into the hot loop.
    - prefix cache (round 11, docs/serving.md "Prefix caching &
      forking"): ``prefix_hits``/``prefix_misses`` — resolution of each
      prefix-declaring submit against the snapshot store (a miss
      launches one internal prefix run); ``prefix_coalesced`` —
      submits that attached to an ALREADY in-flight prefix run instead
      of launching their own; ``prefix_forks`` — lanes seeded by
      scattering a cached/shared snapshot (every prefixed admission);
      ``snapshot_evictions`` — store entries dropped to the byte
      budget. ``admitted``/``retired`` include the internal prefix
      tickets (they really occupy lanes); ``submitted`` counts client
      submits only.
    - fault tolerance (round 12, docs/serving.md "Fault tolerance &
      recovery"): ``diverged`` — lanes the per-window finite check
      quarantined (each also counts under ``failed``); ``recovered`` —
      unfinished requests re-admitted from the WAL at
      ``recover_dir`` startup.
    - mesh failover (round 13, docs/serving.md "Mesh serving & device
      failover"): ``requeued`` — client requests displaced from a
      quarantined DEVICE and re-queued onto surviving shards under
      their original ids. The per-shard view lives in the ``shards``
      gauge list (occupancy, windows, diverged, snapshot bytes,
      quarantined flag per device) plus the ``quarantined_devices``
      count — both refreshed by the server alongside queue depth.
    """

    _COUNTERS = tuple(_COUNTER_HELP)

    def __init__(self) -> None:
        reg = self.registry = MetricsRegistry(namespace="lens_serve")
        self._counters = {
            name: reg.counter(name, help)
            for name, help in _COUNTER_HELP.items()
        }
        # gauges: plain attributes the server refreshes, registered as
        # computed-at-read so the Prometheus exposition and the
        # metrics.jsonl sampler always see the live value
        self.queue_depth = 0
        self.lanes_busy = 0
        self.lanes_total = 0
        self.retraces = 0
        # snapshot-store gauges (refreshed by the server alongside
        # queue depth / busy lanes); snapshot_tiers: one dict per
        # storage tier (entries/bytes/hits/promotions/demotions —
        # round 16, docs/serving.md "Tiered snapshots & speculative
        # warming"), exported like the per-shard gauges
        self.snapshots_resident = 0
        self.snapshot_bytes = 0
        self.snapshot_tiers: Dict[str, Dict[str, int]] = {}
        # mesh gauges: one dict per device shard (index, device,
        # quarantined, lanes, occupancy, windows, diverged,
        # snapshot_bytes) + the quarantined-device count
        self.shards: List[Dict[str, Any]] = []
        self.quarantined_devices = 0
        # result-cache gauges (round 18, docs/serving.md "Suffix dedup
        # & result cache"): entry count and payload bytes of the
        # durable content-addressed result store — its budget is its
        # own, separate from the snapshot tiers above
        self.result_entries = 0
        self.result_bytes = 0
        for name, help, fn in (
            ("queue_depth", "requests waiting for a lane",
             lambda: self.queue_depth),
            ("lanes_busy", "occupied lanes now",
             lambda: self.lanes_busy),
            ("lanes_total", "schedulable lanes (quarantined excluded)",
             lambda: self.lanes_total),
            ("retraces", "window-program compiles beyond the first",
             lambda: self.retraces),
            ("occupancy", "mean lane occupancy (busy/total windows)",
             self.occupancy),
            ("snapshots_resident", "snapshot-store entries resident",
             lambda: self.snapshots_resident),
            ("snapshot_bytes", "snapshot-store resident bytes",
             lambda: self.snapshot_bytes),
            ("quarantined_devices", "device shards quarantined",
             lambda: self.quarantined_devices),
            ("result_entries", "result-cache entries resident",
             lambda: self.result_entries),
            ("result_bytes", "result-cache payload bytes on disk",
             lambda: self.result_bytes),
            ("device_busy_fraction",
             "fraction of the streamed span with a window in flight",
             self.device_busy_fraction),
            ("stream_stalls", "scheduler stalls on stream backpressure",
             lambda: self.stalls),
            ("stream_stall_seconds",
             "scheduler seconds lost to stream backpressure",
             lambda: self.stall_seconds),
        ):
            reg.gauge(name, help, fn=fn)
        self._t0 = time.perf_counter()
        # per finished request: wall seconds submit->admit and
        # submit->done; per window: wall seconds through the pipe.
        # Locked histograms (lens_tpu.obs.metrics.Histogram): the
        # stream thread observes while the scheduler reads/resets.
        self.wait_seconds = reg.histogram(
            "wait_seconds", "request wall seconds submit->admit"
        )
        self.latency_seconds = reg.histogram(
            "latency_seconds", "request wall seconds submit->done"
        )
        self.window_seconds = reg.histogram(
            "window_seconds", "per-window incremental wall seconds"
        )
        reg.gauge(
            "uptime_seconds", "seconds since server construction",
            fn=lambda: time.perf_counter() - self._t0,
        )
        # per streamed window: (dispatched_at, ready_at, streamed_at) —
        # dispatch is when the scheduler enqueued the window program,
        # ready is when its trajectory finished landing host-side, and
        # streamed is when the last sink append for it returned. The
        # pipeline gauges below (device busy fraction, host gap,
        # stream lag) are all derived from these three timestamps.
        # A locked plain list (tuples, not scalars — no Histogram).
        self._stream_lock = threading.Lock()
        self._stream_samples: List[Tuple[float, float, float]] = []
        # scheduler seconds blocked on streamer backpressure (the
        # bounded queue full — host streaming is the bottleneck)
        self.stall_seconds = 0.0
        self.stalls = 0
        # per-tenant counters (TENANT_COUNTERS), created lazily on the
        # first increment for a tenant name. Locked: the front door's
        # HTTP threads (throttles, streamed bytes) and the scheduler
        # thread (admits/rejects) both write.
        self._tenant_lock = threading.Lock()
        self._tenants: Dict[str, Dict[str, int]] = {}

    # -- writers -------------------------------------------------------------

    @property
    def counters(self) -> Dict[str, int]:
        """Plain-dict view of the counter values (the historical read
        surface; writers go through :meth:`inc`)."""
        return {name: c.value for name, c in self._counters.items()}

    def inc(self, name: str, by: int = 1) -> None:
        self._counters[name].inc(by)

    def tenant_inc(
        self, tenant: Optional[str], name: str, by: int = 1
    ) -> None:
        """Bump one tenant-scoped counter (no-op for untenanted
        traffic, so the single-tenant serve path pays one None check).
        Unknown names raise — a typo'd counter must not silently
        create a new column."""
        if tenant is None:
            return
        if name not in TENANT_COUNTERS:
            raise KeyError(
                f"unknown tenant counter {name!r}; known: "
                f"{TENANT_COUNTERS}"
            )
        with self._tenant_lock:
            row = self._tenants.setdefault(
                str(tenant), {k: 0 for k in TENANT_COUNTERS}
            )
            row[name] += int(by)

    @property
    def tenants(self) -> Dict[str, Dict[str, int]]:
        """A consistent copy of the per-tenant counter table
        ({tenant: {admitted, rejected, throttled, streamed_bytes}})."""
        with self._tenant_lock:
            return {t: dict(row) for t, row in self._tenants.items()}

    def observe_request(self, wait_s: float, total_s: float) -> None:
        self.wait_seconds.observe(wait_s)
        self.latency_seconds.observe(total_s)

    def observe_window(self, wall_s: float) -> None:
        self.window_seconds.observe(wall_s)

    def observe_stream(
        self, dispatched_at: float, ready_at: float, streamed_at: float
    ) -> None:
        with self._stream_lock:
            self._stream_samples.append(
                (float(dispatched_at), float(ready_at),
                 float(streamed_at))
            )

    def observe_stall(self, seconds: float) -> None:
        if seconds > 0:
            self.stall_seconds += float(seconds)
            self.stalls += 1

    def reset_samples(self) -> None:
        """Drop accumulated latency/wait/window/stream samples
        (counters stay) — benchmark hygiene after a warmup round, so
        compile-time outliers never dilute the measured percentiles.
        Each buffer clears atomically under its own lock, so an
        observation racing in from the stream thread lands wholly
        before or wholly after the reset — never into a half-cleared
        list (the round-14 race fix; the server still drains the
        streamer first so in-flight windows don't re-sample later)."""
        self.latency_seconds.clear()
        self.wait_seconds.clear()
        self.window_seconds.clear()
        with self._stream_lock:
            self._stream_samples.clear()
        self.stall_seconds = 0.0
        self.stalls = 0

    # -- derived reads -------------------------------------------------------

    @property
    def stream_samples(self) -> List[Tuple[float, float, float]]:
        """A consistent copy of the per-window stream timestamps."""
        with self._stream_lock:
            return list(self._stream_samples)

    def device_busy_fraction(self) -> Optional[float]:
        """Fraction of the streamed span the device had a window in
        flight: per window, busy time runs from max(its dispatch, the
        previous window's ready) to its ready — windows queue behind
        each other on the device, so the previous ready is when this
        one's compute could start. An approximation (ready includes
        the host transfer tail), but it moves the right way: 1.0 means
        the device never waited for the host; the r08 synchronous path
        idled the device for the whole slice/append/flush stretch of
        every window."""
        samples = sorted(self.stream_samples)
        if not samples:
            return None
        span = max(s[2] for s in samples) - samples[0][0]
        if span <= 0:
            return None
        busy = 0.0
        prev_ready = None
        for dispatched, ready, _ in samples:
            start = dispatched if prev_ready is None else max(
                dispatched, prev_ready
            )
            busy += max(ready - start, 0.0)
            prev_ready = ready
        return min(busy / span, 1.0)

    def host_gap_seconds(self) -> List[float]:
        """Per-window host streaming time (ready -> last append)."""
        return [s[2] - s[1] for s in self.stream_samples]

    def stream_lag_seconds(self) -> List[float]:
        """Per-window dispatch -> fully-streamed latency: how far
        behind the device the sinks run (a tailing reader's staleness
        bound)."""
        return [s[2] - s[0] for s in self.stream_samples]

    def avg_window_seconds(self, default: float = 0.1) -> float:
        """Recent mean window wall time — the unit the backpressure
        retry-after hint is quoted in. Falls back to ``default`` before
        the first window has run (cold server, nothing measured)."""
        recent = self.window_seconds.tail(32)
        return sum(recent) / len(recent) if recent else default

    def occupancy(self) -> Optional[float]:
        total = self._counters["lane_windows_total"].value
        if total == 0:
            return None
        return self._counters["lane_windows_busy"].value / total

    # -- export surfaces -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return {
            "counters": self.counters,
            "queue_depth": self.queue_depth,
            "lanes_busy": self.lanes_busy,
            "lanes_total": self.lanes_total,
            "occupancy": self.occupancy(),
            "retraces": self.retraces,
            "snapshots_resident": self.snapshots_resident,
            "snapshot_bytes": self.snapshot_bytes,
            "snapshot_tiers": {
                t: dict(row) for t, row in self.snapshot_tiers.items()
            },
            "result_entries": self.result_entries,
            "result_bytes": self.result_bytes,
            "shards": [dict(s) for s in self.shards],
            "quarantined_devices": self.quarantined_devices,
            "uptime_seconds": time.perf_counter() - self._t0,
            "avg_window_seconds": (
                self.avg_window_seconds() if len(self.window_seconds)
                else None
            ),
            "latency_seconds": self.latency_seconds.percentiles(),
            "wait_seconds": self.wait_seconds.percentiles(),
            "device_busy_fraction": self.device_busy_fraction(),
            "host_gap_seconds": percentiles(self.host_gap_seconds()),
            "stream_lag_seconds": percentiles(self.stream_lag_seconds()),
            "stream_stall_seconds": self.stall_seconds,
            "stream_stalls": self.stalls,
            "tenants": self.tenants,
        }

    def sample_point(self) -> Dict[str, Any]:
        """One ``metrics.jsonl`` record: a wall-clock stamp (seconds
        since server construction) plus the registry's full sample —
        every counter, every gauge read now, every histogram's
        count/sum/percentiles. Appended by the server on the
        ``metrics_interval_s`` cadence; the stream-derived pipeline
        gauges ride along so stream lag exists as history too."""
        point = {"t": time.perf_counter() - self._t0}
        point.update(self.registry.sample())
        lag = self.stream_lag_seconds()
        gap = self.host_gap_seconds()
        point["stream"] = {
            "windows": len(lag),
            "lag": percentiles(lag),
            "host_gap": percentiles(gap),
        }
        if self.shards:
            point["shards"] = [dict(s) for s in self.shards]
        if self.snapshot_tiers:
            point["snapshot_tiers"] = {
                t: dict(row) for t, row in self.snapshot_tiers.items()
            }
        tenants = self.tenants
        if tenants:
            point["tenants"] = tenants
        return point

    def prometheus_text(self) -> str:
        """The Prometheus text exposition format for this server's
        instruments — the pull surface (``SimServer.prometheus_
        metrics()`` refreshes gauges first, same discipline as
        ``metrics()``). Per-shard gauges export with a ``shard``
        label."""
        text = self.registry.prometheus_text()
        lines = [text.rstrip("\n")]
        if self.shards:
            ns = self.registry.namespace
            lines.append(f"# TYPE {ns}_shard_lanes_busy gauge")
            lines.append(f"# TYPE {ns}_shard_windows gauge")
            lines.append(f"# TYPE {ns}_shard_quarantined gauge")
            for s in self.shards:
                label = f'{{shard="{s.get("shard", 0)}"}}'
                lines.append(
                    f"{ns}_shard_lanes_busy{label} "
                    f"{s.get('lanes_busy', 0)}"
                )
                lines.append(
                    f"{ns}_shard_windows{label} {s.get('windows', 0)}"
                )
                lines.append(
                    f"{ns}_shard_quarantined{label} "
                    f"{int(bool(s.get('quarantined')))}"
                )
        if self.snapshot_tiers:
            ns = self.registry.namespace
            for col in (
                "entries", "bytes", "hits", "promotions", "demotions",
            ):
                lines.append(f"# TYPE {ns}_snapshot_tier_{col} gauge")
                for t, row in sorted(self.snapshot_tiers.items()):
                    lines.append(
                        f'{ns}_snapshot_tier_{col}{{tier="{t}"}} '
                        f"{row.get(col, 0)}"
                    )
        tenants = self.tenants
        if tenants:
            ns = self.registry.namespace

            def esc(label: str) -> str:
                # Prometheus label-value escaping: a tenant name with
                # a quote/backslash/newline must not corrupt the
                # whole exposition
                return (
                    label.replace("\\", "\\\\")
                    .replace('"', '\\"')
                    .replace("\n", "\\n")
                )

            for name in TENANT_COUNTERS:
                lines.append(f"# TYPE {ns}_tenant_{name}_total counter")
                for t in sorted(tenants):
                    lines.append(
                        f'{ns}_tenant_{name}_total'
                        f'{{tenant="{esc(t)}"}} '
                        f"{tenants[t][name]}"
                    )
        return "\n".join(lines) + "\n"


def request_timing_row(ticket, t0: float) -> Dict[str, Any]:
    """One per-request row of the ``server_meta.json`` timing table:
    the request's lifecycle wall times (seconds since server
    construction, ``None`` where a stage never happened), derived from
    the span marks the scheduler stamps on the ticket. Replaces the
    ad-hoc "read the latency percentile and guess" workflow: the
    sidecar now names when each request queued, admitted, first hit a
    device, finished streaming, and retired."""

    def rel(at: Optional[float]) -> Optional[float]:
        return None if at is None else round(at - t0, 6)

    return {
        "rid": ticket.request_id,
        "status": ticket.status,
        "shard": ticket.shard,
        "steps_done": ticket.steps_done,
        "queued": rel(ticket.submitted_at),
        "admitted": rel(ticket.admitted_at),
        "first_window": rel(ticket.first_window_at),
        "last_streamed": rel(ticket.streamed_at),
        "retired": rel(ticket.finished_at),
        "error": ticket.error,
    }


def write_server_meta(
    out_dir: str,
    config: Mapping[str, Any],
    metrics: ServerMetrics,
    requests: Optional[List[Dict[str, Any]]] = None,
) -> str:
    """The ``server_meta.json`` sidecar: serving config + final counter
    snapshot + (round 14) the per-request timing table, beside the
    per-request result logs — the serve analogue of the run path's
    ``colony_meta.json`` (provenance that is not recoverable from the
    data files themselves)."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "server_meta.json")
    payload = {"config": dict(config), **metrics.snapshot()}
    if requests is not None:
        payload["requests"] = requests
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, default=str)
    os.replace(tmp, path)
    return path
