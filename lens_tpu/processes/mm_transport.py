"""Michaelis–Menten nutrient transport — the spatial-coupling Process.

Benchmark config 2 (BASELINE.json): "10k agents on 256x256 diffusion
lattice, Michaelis–Menten transport Process". Fills the reference's
transport-process slot for lattice-coupled runs (reconstructed:
``lens/processes/*transport*.py`` + exchange semantics of
``lens/actor/inner.py`` ``generate_inner_update``, SURVEY.md §3.2).

Port conventions for spatially coupled processes:

- ``external``: local environment concentrations at the cell's bin.
  Declared ``_updater: null`` — the process never writes it; the spatial
  wrapper overwrites it from the field gather each window (the
  ENVIRONMENT_UPDATE direction).
- ``exchange``: accumulated NET SECRETION in environment units (negative
  = uptake). The spatial wrapper scatters it into the field and zeroes it
  (the CELL_UPDATE direction).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lens_tpu.core.process import Process
from lens_tpu.processes import register


@register
class MichaelisMentenTransport(Process):
    name = "mm_transport"

    defaults = {
        "vmax": 0.1,      # mM/s at saturation
        "km": 0.5,        # mM
        "yield_": 0.1,    # internal pool produced per unit taken up
        "k_consume": 0.05,  # 1/s first-order drain of the internal pool
        "molecule": "glucose",
        # Schema defaults for the external concentration and the internal
        # pool. Shared-path declarations must agree across processes
        # (core.engine), so composites wiring several processes onto one
        # variable set these consistently. A nonzero ``internal_default``
        # boots every cell with a yolk — REQUIRED when a starvation
        # DeathTrigger watches the pool, else newborn boot cells (pool 0)
        # die at t=0 before their first meal.
        "external_default": 10.0,
        "internal_default": 0.0,
    }

    def ports_schema(self):
        mol = self.config["molecule"]
        return {
            "external": {
                mol: {
                    "_default": float(self.config["external_default"]),
                    "_updater": "null",
                    "_divider": "copy",
                },
            },
            "internal": {
                f"{mol}_internal": {
                    "_default": float(self.config["internal_default"]),
                    "_updater": "nonnegative_accumulate",
                    "_divider": "split",
                },
            },
            "exchange": {
                f"{mol}_exchange": {
                    "_default": 0.0,
                    "_updater": "accumulate",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
        }

    def next_update(self, timestep, states):
        mol = self.config["molecule"]
        c = self.config
        s_ext = states["external"][mol]
        pool = states["internal"][f"{mol}_internal"]
        uptake = c["vmax"] * s_ext / (c["km"] + s_ext) * timestep
        # cannot take up more than is locally available
        uptake = jnp.minimum(uptake, s_ext)
        return {
            "internal": {
                f"{mol}_internal": c["yield_"] * uptake
                - c["k_consume"] * pool * timestep
            },
            "exchange": {f"{mol}_exchange": -uptake},
        }


@register
class BrownianMotility(Process):
    """Diffusive cell movement on the lattice.

    The reference's run/tumble motility lives in the outer lattice agent
    (reconstructed: ``lens/environment/lattice.py`` ``update_locations``,
    SURVEY.md §2); here movement is an ordinary stochastic Process owning
    the cell's ``location`` so chemotactic variants can replace it without
    touching the environment code.
    """

    name = "brownian_motility"
    stochastic = True

    defaults = {
        "sigma": 0.5,    # um / sqrt(s) random-walk scale
        # Optional clip bounds (um). Default None: unbounded — when run
        # under a SpatialColony the wrapper clips to the lattice domain
        # (the geometry lives in one place); set explicitly only for
        # standalone use.
        "domain": None,
    }

    def ports_schema(self):
        return {
            "boundary": {
                "location": {
                    "_default": jnp.zeros(2, jnp.float32),
                    "_updater": "set",
                    # division placement: daughters separate by a cell
                    # length along a random axis (core.state._div_offset)
                    "_divider": "offset",
                },
            },
        }

    def next_update(self, timestep, states, key=None):
        loc = states["boundary"]["location"]
        step = self.config["sigma"] * jnp.sqrt(timestep) * jax.random.normal(
            key, (2,)
        )
        new = loc + step
        if self.config["domain"] is not None:
            h, w = self.config["domain"]
            new = jnp.clip(new, jnp.zeros(2), jnp.asarray([h, w]) - 1e-3)
        return {"boundary": {"location": new}}
