"""Glucose uptake (PTS-style) — the 2-species ODE transport Process.

Benchmark config 0 (BASELINE.json): "Single E. coli agent, 2-species
glucose-uptake ODE Process, 100 sim-sec". The reference's kinetic transport
process integrates an uptake ODE with ``scipy.odeint`` inside
``next_update`` (reconstructed: ``lens/processes/*transport*.py``,
SURVEY.md §2); here the window is integrated with the framework's
scan-based RK4 (``ops.integrate.odeint_window``).

Model: Michaelis–Menten uptake of external glucose into an internal pool
that is consumed first-order (feeding growth downstream)::

    uptake  = vmax * G_ext / (km + G_ext)          [mM/s]
    dG_ext/dt = -uptake * density                  (environment drawdown)
    dG_int/dt = +uptake - k_consume * G_int

The accumulated external drawdown is also reported on an ``exchange`` port
so the lattice layer can apply it to the cell's local field bin
(SURVEY.md §3.2 exchange semantics).
"""

from __future__ import annotations

import jax.numpy as jnp

from lens_tpu.core.process import Process
from lens_tpu.ops.integrate import odeint_window
from lens_tpu.processes import register


@register
class GlucosePTS(Process):
    name = "glucose_pts"

    defaults = {
        "vmax": 1.5,        # mM/s max uptake rate
        "km": 0.2,          # mM half-saturation
        "k_consume": 0.1,   # 1/s internal consumption
        "density": 0.01,    # env drawdown per unit uptake (cell/env volume ratio)
        "substeps": 10,     # RK4 substeps per process window (static)
        "method": "rk4",
    }

    def ports_schema(self):
        return {
            "internal": {
                "glucose_internal": {
                    "_default": 0.0,
                    "_updater": "nonnegative_accumulate",
                    "_divider": "split",
                },
            },
            "external": {
                "glucose_external": {
                    "_default": 10.0,
                    "_updater": "nonnegative_accumulate",
                    "_divider": "copy",   # a concentration, not an amount
                },
            },
            "exchange": {
                # net SECRETION this window (negative = uptake), in env
                # concentration units; consumed (zeroed) by the lattice
                # exchange step. Sign convention shared by all spatially
                # coupled processes (see processes/mm_transport.py).
                "glucose_flux": {
                    "_default": 0.0,
                    "_updater": "accumulate",
                    "_divider": "zero",
                },
            },
        }

    def _rhs(self, t, y, args):
        g_ext, g_int = y
        c = self.config
        uptake = c["vmax"] * g_ext / (c["km"] + g_ext)
        return (
            -uptake * c["density"],
            uptake - c["k_consume"] * g_int,
        )

    def next_update(self, timestep, states):
        g_ext0 = states["external"]["glucose_external"]
        g_int0 = states["internal"]["glucose_internal"]
        n = max(int(self.config["substeps"]), 1)
        g_ext, g_int = odeint_window(
            self._rhs,
            (g_ext0, g_int0),
            0.0,
            jnp.float32(timestep) / n,
            n,
            method=self.config["method"],
        )
        return {
            "internal": {"glucose_internal": g_int - g_int0},
            "external": {"glucose_external": g_ext - g_ext0},
            "exchange": {"glucose_flux": g_ext - g_ext0},
        }
