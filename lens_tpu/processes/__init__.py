"""Process library + registry.

The reference maps agent-type names to constructors in its boot layer
(reconstructed: ``lens/actor/boot.py``, SURVEY.md §1 L5). The rebuild keeps
a simple name -> class registry so experiment configs can be pure data.
"""

from __future__ import annotations

from typing import Dict, Type

from lens_tpu.core.process import Process

process_registry: Dict[str, Type[Process]] = {}


def register(cls: Type[Process]) -> Type[Process]:
    process_registry[cls.name] = cls
    return cls


# Import for registration side effects.
from lens_tpu.processes.glucose_pts import GlucosePTS  # noqa: E402
from lens_tpu.processes.toggle_switch import ToggleSwitch  # noqa: E402
from lens_tpu.processes.growth import (  # noqa: E402
    DeathTrigger,
    DivideTrigger,
    Growth,
    Lysis,
)
from lens_tpu.processes.mm_transport import (  # noqa: E402
    BrownianMotility,
    MichaelisMentenTransport,
)
from lens_tpu.processes.stochastic_expression import (  # noqa: E402
    StochasticExpression,
)
from lens_tpu.processes.genome_expression import (  # noqa: E402
    GenomeExpression,
)
from lens_tpu.processes.derivers import (  # noqa: E402
    DeriveConcentrations,
    DeriveVolume,
    DivideCondition,
    MassGrowth,
)
from lens_tpu.processes.chemotaxis import (  # noqa: E402
    FlagellarMotor,
    MWCChemoreceptor,
    RunTumbleMotility,
)
from lens_tpu.processes.expression import (  # noqa: E402
    Complexation,
    Degradation,
    Transcription,
    Translation,
)
from lens_tpu.processes.metabolism import Metabolism  # noqa: E402
from lens_tpu.processes.fba_metabolism import FBAMetabolism  # noqa: E402
from lens_tpu.processes.transport_lookup import TransportLookup  # noqa: E402

__all__ = [
    "process_registry",
    "register",
    "GlucosePTS",
    "ToggleSwitch",
    "Growth",
    "Lysis",
    "DeathTrigger",
    "DivideTrigger",
    "MichaelisMentenTransport",
    "BrownianMotility",
    "StochasticExpression",
    "GenomeExpression",
    "DeriveConcentrations",
    "DeriveVolume",
    "DivideCondition",
    "MassGrowth",
    "FlagellarMotor",
    "MWCChemoreceptor",
    "RunTumbleMotility",
    "Complexation",
    "Degradation",
    "Transcription",
    "Translation",
    "Metabolism",
    "FBAMetabolism",
    "TransportLookup",
]
