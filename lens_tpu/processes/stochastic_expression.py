"""Stochastic gene expression — tau-leap transcription/translation/decay.

Fills the reference's stochastic expression slot (reconstructed:
``lens/processes/`` minimal transcription/translation/degradation
modules, SURVEY.md §2 "Gene expression processes") with the TPU-native
tau-leap kernel (ops.gillespie). Benchmark config 4 (BASELINE.json):
"100k mixed-species colony, hybrid tau-leap Gillespie + ODE per agent" —
this is the Gillespie half of that hybrid.

Reaction network (counts, one gene):

    gene      --k_tx-->   gene + mRNA        (transcription)
    mRNA      --k_tl-->   mRNA + protein     (translation)
    mRNA      --d_m-->    0                  (mRNA decay)
    protein   --d_p-->    0                  (protein decay)

**Mixed-species colonies without branching:** the kinetic rates are
declared as *state variables* (``_updater: null`` — constants the process
reads but never writes), not static config. A colony overrides them
per-agent at ``initial_state`` (a [capacity]-shaped array), so one SPMD
program steps a population whose agents carry different parameters —
the rebuild's answer to the reference running different process configs
in different OS processes (SURVEY.md §7 "heterogeneity under SPMD").
Stationary anchors for tests: mRNA ~ Poisson(k_tx/d_m);
E[protein] = k_tx k_tl / (d_m d_p).
"""

from __future__ import annotations

import jax.numpy as jnp

from lens_tpu.core.process import Process
from lens_tpu.ops.gillespie import tau_leap_window
from lens_tpu.ops.sampling import check_sampler, check_threshold
from lens_tpu.processes import register

# stoichiometry [R=4, S=2]; species order: (mRNA, protein)
_STOICH = jnp.asarray(
    [
        [1.0, 0.0],   # transcription
        [0.0, 1.0],   # translation
        [-1.0, 0.0],  # mRNA decay
        [0.0, -1.0],  # protein decay
    ]
)


@register
class StochasticExpression(Process):
    name = "stochastic_expression"
    stochastic = True

    defaults = {
        "k_tx": 0.5,   # transcripts/s (default; per-agent override via state)
        "k_tl": 2.0,   # proteins per mRNA per s
        "d_m": 0.1,    # 1/s mRNA decay
        "d_p": 0.02,   # 1/s protein decay
        "substeps": 10,
        # Poisson event sampler (ops.sampling): "hybrid" draws one fused
        # uniform block per window and pushes it through the batched
        # inverse-CDF fast path; "exact" keeps jax.random.poisson with
        # per-substep key splits — bitwise-identical to pre-fast-path
        # checkpoints, the oracle/resume escape hatch.
        "sampler": "hybrid",
        "sampler_threshold": 10.0,  # mean-events regime split
    }

    def __init__(self, config=None):
        super().__init__(config)
        check_sampler(self.config["sampler"])  # typo -> fail at build
        check_threshold(self.config["sampler_threshold"])

    def ports_schema(self):
        c = self.config
        count = lambda: {
            "_default": 0.0,
            "_updater": "nonnegative_accumulate",
            "_divider": "binomial",
        }
        rate = lambda default: {
            "_default": float(default),
            "_updater": "null",     # read-only: the per-agent parameter trick
            "_divider": "copy",
            "_emit": False,
        }
        return {
            "counts": {"mrna": count(), "protein": count()},
            "rates": {
                "k_tx": rate(c["k_tx"]),
                "k_tl": rate(c["k_tl"]),
                "d_m": rate(c["d_m"]),
                "d_p": rate(c["d_p"]),
            },
        }

    def next_update(self, timestep, states, key=None):
        counts = jnp.stack(
            [states["counts"]["mrna"], states["counts"]["protein"]]
        )
        r = states["rates"]

        def propensities(x):
            m, p = x[0], x[1]
            return jnp.stack(
                [r["k_tx"], r["k_tl"] * m, r["d_m"] * m, r["d_p"] * p]
            )

        new = tau_leap_window(
            key, counts, _STOICH, propensities, timestep,
            int(self.config["substeps"]),
            sampler=self.config["sampler"],
            threshold=float(self.config["sampler_threshold"]),
        )
        return {
            "counts": {
                "mrna": new[0] - counts[0],
                "protein": new[1] - counts[1],
            },
        }
