"""Regulated kinetic metabolism (Covert–Palsson 2002 lineage).

The reference's metabolism Process consumes exchange fluxes and produces
biomass growth through a regulated flux model — reaction fluxes over a
stoichiometric matrix, gated by boolean regulation rules evaluated against
the current state (reconstructed: ``lens/processes/…metabolism….py``,
SURVEY.md §2 "Metabolism process"). Whether the original solves an exact
LP (FBA) could not be verified (mount empty); SURVEY.md §7 ranks batched
LP-on-TPU as research-grade and directs v1 to kinetic/lookup metabolism —
**this module is that v1**, and the FBA gap is documented here: an exact
simplex per agent per step is data-dependent control flow that XLA cannot
tile onto the MXU; a future version can batch a fixed-iteration
primal-dual/ADMM solve (fixed shapes, dense linear algebra) if exact FBA
parity is required.

Design — everything is one dense matmul per step, MXU-friendly:

- ``stoichiometry``: [n_reactions, n_species] dense matrix (static).
- flux_i = vmax_i * prod_j MM(substrate_j) * regulation_i(state)
  (kinetic rate laws per reaction, vectorized).
- dS = dt * fluxes @ stoichiometry  (THE matmul; at 100k agents this is
  a [100k, R] x [R, S] batched contraction on the MXU).
- biomass: a designated species row feeds mass growth.

Regulation rules come from :mod:`lens_tpu.utils.regulation_logic` and are
compiled once at construction; their inputs read the same ``metabolites``
store the fluxes write, closing the Covert–Palsson regulatory loop.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.core.process import Process
from lens_tpu.processes import register
from lens_tpu.utils.rate_laws import michaelis_menten
from lens_tpu.utils.regulation_logic import compile_rule

#: A minimal E. coli-ish core network (glucose -> biomass + acetate
#: overflow, acetate re-uptake when glucose is gone — the diauxie the
#: Covert-Palsson regulated model is known for).
CORE_NETWORK = {
    "species": ["glc", "ace", "atp", "biomass"],
    "reactions": {
        # name: (stoich dict, vmax, substrates with Km, regulation rule)
        "glycolysis": {
            "stoich": {"glc": -1.0, "atp": 2.0, "ace": 0.6, "biomass": 0.1},
            "vmax": 0.12,
            "km": {"glc": 0.5},
            "rule": "",
        },
        "acetate_uptake": {
            "stoich": {"ace": -1.0, "atp": 1.0, "biomass": 0.05},
            "vmax": 0.05,
            "km": {"ace": 1.0},
            "rule": "not glc",  # catabolite repression: off while glucose present
        },
        "maintenance": {
            "stoich": {"atp": -1.0},
            "vmax": 0.02,
            "km": {"atp": 0.1},
            "rule": "",
        },
    },
    "biomass_species": "biomass",
}


@register
class Metabolism(Process):
    """Regulated kinetic flux metabolism over a dense stoichiometric matrix.

    Ports:

    - ``metabolites``: internal metabolite pools (mM), one variable per
      species in the network.
    - ``global``: ``mass`` (fg) — biomass production accrues here through
      ``mass_yield`` (fg per mM·fL of biomass flux).
    - ``fluxes`` (emit-only): last step's reaction fluxes for analysis.
    """

    name = "metabolism"

    defaults = {
        "network": CORE_NETWORK,
        "mass_yield": 100.0,     # fg mass per unit biomass species produced
        "regulation_threshold": 0.05,  # mM presence threshold for rules
    }

    def __init__(self, config=None):
        super().__init__(config)
        net = self.config["network"]
        self.species: Tuple[str, ...] = tuple(net["species"])
        self.reactions: Tuple[str, ...] = tuple(net["reactions"])
        self.biomass_species: str = net["biomass_species"]
        n_r, n_s = len(self.reactions), len(self.species)
        stoich = np.zeros((n_r, n_s), np.float32)
        vmax = np.zeros((n_r,), np.float32)
        self._kms: Dict[int, Dict[int, float]] = {}
        self._rules = {}
        s_index = {s: j for j, s in enumerate(self.species)}
        for i, name in enumerate(self.reactions):
            rxn = net["reactions"][name]
            for s, coeff in rxn["stoich"].items():
                stoich[i, s_index[s]] = coeff
            vmax[i] = rxn["vmax"]
            self._kms[i] = {s_index[s]: km for s, km in rxn["km"].items()}
            rule = rxn.get("rule", "")
            if rule:
                self._rules[i] = compile_rule(
                    rule, threshold=self.config["regulation_threshold"]
                )
        self.stoichiometry = jnp.asarray(stoich)   # [R, S]
        self.vmax = jnp.asarray(vmax)              # [R]
        for rule in self._rules.values():
            for dep in rule.names:
                if dep not in s_index:
                    raise ValueError(
                        f"regulation rule {rule.source!r} references "
                        f"{dep!r}, not a network species"
                    )

    def ports_schema(self):
        return {
            "metabolites": {
                s: {
                    "_default": 1.0 if s != self.biomass_species else 0.0,
                    "_updater": "nonnegative_accumulate",
                    "_divider": "copy",  # concentrations are intensive
                }
                for s in self.species
            },
            "global": {
                "mass": {
                    "_default": 330.0,
                    "_updater": "accumulate",
                    "_divider": "split",
                },
            },
            "fluxes": {
                "reaction_fluxes": {
                    "_default": jnp.zeros(len(self.reactions), jnp.float32),
                    "_updater": "set",
                    "_divider": "copy",
                },
            },
        }

    def next_update(self, timestep, states):
        pools = jnp.stack(
            [states["metabolites"][s] for s in self.species]
        )  # [S]
        saturation = jnp.ones((len(self.reactions),))
        for i, kms in self._kms.items():
            for j, km in kms.items():
                saturation = saturation.at[i].mul(
                    michaelis_menten(pools[j], 1.0, km)
                )
        gates = jnp.ones((len(self.reactions),))
        env = {s: pools[j] for j, s in enumerate(self.species)}
        for i, rule in self._rules.items():
            gates = gates.at[i].set(rule(env))
        fluxes = self.vmax * saturation * gates  # [R], mM/s
        # f32 precision: the TPU's default bf16 matmul would leak ~0.4%
        # of every flux into/out of the pools (mass-conservation breaker)
        dpools = timestep * jnp.matmul(
            fluxes, self.stoichiometry,
            precision=jax.lax.Precision.HIGHEST,
        )  # [S] — the matmul
        biomass_idx = self.species.index(self.biomass_species)
        dmass = self.config["mass_yield"] * jnp.maximum(
            dpools[biomass_idx], 0.0
        )
        update = {
            "metabolites": {
                s: dpools[j] for j, s in enumerate(self.species)
            },
            "global": {"mass": dmass},
            "fluxes": {"reaction_fluxes": fluxes},
        }
        # biomass is drained into mass (keeps the pool from growing unboundedly)
        update["metabolites"][self.biomass_species] = (
            dpools[biomass_idx] - jnp.maximum(dpools[biomass_idx], 0.0)
        )
        return update
