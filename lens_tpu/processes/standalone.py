"""Standalone process runs: the reference's per-process dev harness.

Every reference process file carries a runnable ``__main__`` that steps
the process alone against dict states and saves a plot — the de-facto
unit-test harness (reconstructed: SURVEY.md §3.4 "standalone process
run"). This module is that harness for ANY registered Process, exposed
both as a library call and through the CLI::

    python -m lens_tpu demo mm_transport --time 200 --out out/demo
    python -m lens_tpu demo stochastic_expression --time 300

The wiring is automatic: each port maps to a store of the same name
(identity topology), the compartment is built from the process's own
declared schema, and the timeseries of every emitted variable is plotted
with :func:`lens_tpu.analysis.plot_timeseries`.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Mapping, Optional, Tuple

import jax

from lens_tpu.core.engine import Compartment
from lens_tpu.core.process import Process


def standalone_compartment(process: Process) -> Compartment:
    """Wrap one process in a Compartment with identity port wiring."""
    topology = {"process": {port: (port,) for port in process.ports_schema()}}
    return Compartment(processes={"process": process}, topology=topology)


def run_standalone(
    process: Process,
    total_time: float = 100.0,
    timestep: float = 1.0,
    overrides: Optional[Mapping] = None,
    seed: int = 0,
    emit_every: int = 1,
) -> Tuple[dict, dict]:
    """Step ``process`` alone; return ``(final_state, trajectory)``.

    The trajectory stacks every emitted variable over time — exactly the
    state a reference process's ``__main__`` would collect into its
    timeseries dict.
    """
    comp = standalone_compartment(process)
    state = comp.initial_state(overrides)
    key = jax.random.PRNGKey(seed) if comp.has_stochastic else None
    run = jax.jit(
        lambda s: comp.run(
            s, total_time, timestep, emit_every=emit_every, key=key
        )
    )
    return run(state)


def demo(
    process_name: str,
    total_time: float = 100.0,
    timestep: float = 1.0,
    config: Optional[Mapping[str, Any]] = None,
    out_dir: str = "out",
    seed: int = 0,
) -> Dict[str, str]:
    """Run a registered process standalone and render its timeseries.

    Returns ``{"plot": path}``. The reference saved per-process plots to
    ``out/`` the same way.
    """
    from lens_tpu.analysis import plot_timeseries
    from lens_tpu.processes import process_registry

    if process_name not in process_registry:
        raise KeyError(
            f"unknown process {process_name!r}; known: "
            f"{sorted(process_registry)}"
        )
    process = process_registry[process_name](config)
    _, trajectory = run_standalone(
        process, total_time=total_time, timestep=timestep, seed=seed
    )
    os.makedirs(out_dir, exist_ok=True)
    plot = plot_timeseries(
        trajectory,
        out_path=os.path.join(out_dir, f"{process_name}.png"),
    )
    return {"plot": plot}
