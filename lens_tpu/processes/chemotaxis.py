"""Chemotaxis: MWC chemoreceptor cluster + flagellar motor + run/tumble.

The reference models E. coli chemotaxis as two coupled Processes — a
Monod–Wyman–Changeux receptor-cluster model producing cluster activity
from ligand concentration (with slow methylation adaptation), and a
flagellar-motor process converting activity (a CheY-P proxy) into
stochastic run/tumble switching — with the actual cell displacement applied
by the lattice's motility code (reconstructed:
``lens/processes/…chemoreceptor/motor….py`` and
``lens/environment/lattice.py`` ``update_locations``, SURVEY.md §2
"Chemotaxis processes"). The rebuild keeps the same three-stage split but
makes displacement a Process too (``RunTumbleMotility``) so the
environment owns geometry only.

TPU notes: the motor's two-state switching is a per-agent Bernoulli draw
(fixed-shape, ``jax.random``), and adaptation is a single exponential
relaxation — everything stays branch-free under ``vmap`` across 100k
agents.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lens_tpu.core.process import Process
from lens_tpu.processes import register


@register
class MWCChemoreceptor(Process):
    """MWC receptor-cluster activity with methylation adaptation.

    Free-energy model (standard Tar/Tsr MWC form):

        F = N * [ m_eff(methyl) + log( (1 + L/K_off) / (1 + L/K_on) ) ]
        activity = 1 / (1 + exp(F))

    Methylation relaxes activity toward ``adapted_activity`` with rate
    ``k_adapt`` — perfect adaptation on timescales >> 1/k_adapt, so the
    cluster responds to concentration *changes* (temporal gradient
    sensing), which is what makes run/tumble climb gradients.
    """

    name = "chemoreceptor"

    defaults = {
        "n_receptors": 6.0,      # cluster cooperativity
        "k_off": 0.02,           # mM, dissociation constant (inactive state)
        "k_on": 0.5,             # mM, dissociation constant (active state)
        "m_eff_scale": 1.0,      # free-energy per methylation unit (kT)
        "adapted_activity": 1.0 / 3.0,
        "k_adapt": 0.1,          # 1/s methylation relaxation rate
        "molecule": "glucose",   # attractant field name
        # Shared-path declarations must agree across processes; composites
        # that also wire transport onto the same boundary variable set
        # this to the same value (see mm_transport.external_default).
        "external_default": 0.1,
    }

    def ports_schema(self):
        mol = self.config["molecule"]
        return {
            "external": {
                mol: {
                    "_default": float(self.config["external_default"]),
                    "_updater": "null",
                    "_divider": "copy",
                },
            },
            "internal": {
                "methyl": {
                    "_default": 2.0,
                    "_updater": "accumulate",
                    "_divider": "copy",
                },
                "chemoreceptor_activity": {
                    "_default": 1.0 / 3.0,
                    "_updater": "set",
                    "_divider": "copy",
                },
            },
        }

    # The MWC free energy is F = N * (f_methyl(m) + f_ligand(L)). Both
    # _activity and adapted_methyl (its inverse in m) are written in terms
    # of the two helpers below — change the functional form THERE and the
    # forward/inverse pair cannot drift apart.

    def _f_ligand(self, ligand):
        c = self.config
        ligand = jnp.maximum(jnp.asarray(ligand, jnp.float32), 0.0)
        return jnp.log1p(ligand / c["k_off"]) - jnp.log1p(ligand / c["k_on"])

    def _f_methyl(self, methyl):
        # methylation lowers the free energy of the active state
        return 1.0 - 0.5 * methyl * self.config["m_eff_scale"]

    def _methyl_for_f(self, f_methyl):
        """Inverse of ``_f_methyl``."""
        return 2.0 * (1.0 - f_methyl) / self.config["m_eff_scale"]

    def _activity(self, ligand, methyl):
        c = self.config
        free_energy = c["n_receptors"] * (
            self._f_methyl(methyl) + self._f_ligand(ligand)
        )
        return 1.0 / (1.0 + jnp.exp(free_energy))

    def adapted_methyl(self, ligand):
        """Methylation level at which activity == adapted_activity for a
        given ambient ligand concentration.

        Cells dropped into a field far from their adapted state spend
        O(1/k_adapt · ΔF) seconds deaf to gradients while methylation
        catches up; initialize ``methyl`` with this to start at the
        working point (the reference's cells start pre-adapted the same
        way).
        """
        c = self.config
        f_star = jnp.log(1.0 / c["adapted_activity"] - 1.0)
        # N * (f_methyl + f_ligand) = F*  ->  f_methyl, then invert in m
        f_methyl = f_star / c["n_receptors"] - self._f_ligand(ligand)
        return self._methyl_for_f(f_methyl)

    def next_update(self, timestep, states):
        c = self.config
        ligand = states["external"][c["molecule"]]
        methyl = states["internal"]["methyl"]
        activity = self._activity(ligand, methyl)
        # Adaptation: methylation integrates the activity error. dF/dm =
        # -N*m_eff_scale/2 < 0, so higher methyl -> higher activity; to pull
        # activity back UP to the setpoint when it is low we must ADD methyl
        # when activity < adapted_activity.
        dmethyl = c["k_adapt"] * (c["adapted_activity"] - activity) * timestep
        return {
            "internal": {
                "methyl": dmethyl,
                "chemoreceptor_activity": activity,
            },
        }


@register
class FlagellarMotor(Process):
    """Two-state motor switching: activity (CheY-P proxy) -> run/tumble.

    ``motor_state`` is 0.0 (run / CCW) or 1.0 (tumble / CW). Switching
    propensities follow the activity-dependent form: high receptor
    activity -> high CheY-P -> more CW (tumble). Transitions are sampled
    per timestep from the exponential waiting-time discretization
    ``p = 1 - exp(-k dt)`` — a fixed-shape Bernoulli draw per agent.
    """

    name = "flagellar_motor"
    stochastic = True

    defaults = {
        "k_run_to_tumble_max": 2.0,   # 1/s at activity = 1
        "k_tumble_to_run": 2.0,       # 1/s (mean tumble ~0.5 s)
        "activity_exponent": 4.0,     # ultrasensitivity of CheY-P -> CW bias
        "adapted_activity": 1.0 / 3.0,
    }

    def ports_schema(self):
        # chemoreceptor_activity is read-only here; its declaration must
        # match the receptor's (shared-variable declarations must agree —
        # the engine rejects conflicts, core.engine._build_schema).
        return {
            "internal": {
                "chemoreceptor_activity": {
                    "_default": 1.0 / 3.0,
                    "_updater": "set",
                    "_divider": "copy",
                },
                "motor_state": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
        }

    def next_update(self, timestep, states, key=None):
        c = self.config
        activity = states["internal"]["chemoreceptor_activity"]
        motor = states["internal"]["motor_state"]
        # normalized ultrasensitive CW bias: k_max/2 at adapted activity
        rel = jnp.maximum(activity / c["adapted_activity"], 0.0)
        k_rt = c["k_run_to_tumble_max"] * (rel**c["activity_exponent"]) / (
            1.0 + rel ** c["activity_exponent"]
        )
        k_tr = c["k_tumble_to_run"]
        p_switch = jnp.where(
            motor > 0.5,
            1.0 - jnp.exp(-k_tr * timestep),
            1.0 - jnp.exp(-k_rt * timestep),
        )
        u = jax.random.uniform(key, jnp.shape(motor))
        switched = (u < p_switch).astype(jnp.float32)
        new_motor = jnp.where(switched > 0.5, 1.0 - motor, motor)
        return {"internal": {"motor_state": new_motor}}


@register
class RunTumbleMotility(Process):
    """Displacement from the motor state: run straight, tumble reorients.

    Running moves the cell ``speed * dt`` along its heading; tumbling
    freezes it and draws a fresh uniform heading (plus small rotational
    diffusion while running). The spatial wrapper clips locations to the
    lattice domain (geometry lives in the environment, as in the
    reference).
    """

    name = "run_tumble_motility"
    stochastic = True

    defaults = {
        "speed": 20.0,          # um/s run speed (E. coli-ish)
        "rot_diffusion": 0.1,   # rad^2/s rotational diffusion while running
    }

    def ports_schema(self):
        return {
            "boundary": {
                "location": {
                    "_default": jnp.zeros(2, jnp.float32),
                    "_updater": "set",
                    # division placement: daughters separate by a cell
                    # length along a random axis (core.state._div_offset)
                    "_divider": "offset",
                },
                "heading": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "copy",
                    "_emit": False,
                },
            },
            "internal": {
                # read-only view of the motor's variable (declaration
                # matches FlagellarMotor's — shared paths must agree)
                "motor_state": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
        }

    def next_update(self, timestep, states, key=None):
        c = self.config
        loc = states["boundary"]["location"]
        heading = states["boundary"]["heading"]
        motor = states["internal"]["motor_state"]
        k_tumble, k_rot = jax.random.split(key)
        new_heading_tumble = jax.random.uniform(
            k_tumble, jnp.shape(heading), minval=0.0, maxval=2.0 * jnp.pi
        )
        rot = jnp.sqrt(2.0 * c["rot_diffusion"] * timestep) * jax.random.normal(
            k_rot, jnp.shape(heading)
        )
        running = motor < 0.5
        heading = jnp.where(running, heading + rot, new_heading_tumble)
        step = jnp.where(running, c["speed"] * timestep, 0.0)
        delta = step * jnp.stack([jnp.cos(heading), jnp.sin(heading)])
        return {
            "boundary": {
                "location": loc + delta,
                "heading": jnp.mod(heading, 2.0 * jnp.pi),
            },
        }
