"""Deterministic gene-expression processes: transcription, translation,
degradation, complexation.

The reference carries a family of minimal expression Processes operating on
molecule counts — transcription (with optional regulation), translation,
first-order RNA/protein degradation, and stoichiometric complexation, plus
a polymerization helper (reconstructed: ``lens/processes/`` expression
modules, SURVEY.md §2 "Gene expression processes"). These are the
deterministic (mean-field) counterparts of
:class:`lens_tpu.processes.stochastic_expression.StochasticExpression`;
composites mix the two freely and the engine's per-step merge couples them.

All four processes share a ``counts`` store convention: every species is a
real-valued count with ``_updater: nonnegative_accumulate`` and
``_divider: binomial`` (counts partition stochastically at division).

Gene regulation uses :mod:`lens_tpu.utils.regulation_logic` rules keyed by
transcript, evaluated against the merged counts view — the rebuild of the
reference's boolean regulation parser (``lens/utils/regulation_logic.py``).

**Stochastic option.** Transcription, Translation, and Degradation accept
``sampler: None | "hybrid" | "exact"``. ``None`` (default) keeps the
mean-field flux exactly as before. A sampler name turns each step's flux
into discrete Poisson event counts with that expectation — the
low-copy-number regime the mean-field form washes out — drawn as ONE
bulk block per process per step through :mod:`lens_tpu.ops.sampling`
(``"hybrid"`` = the batched fast path, ``"exact"`` =
``jax.random.poisson``). The process then declares itself stochastic so
the engine supplies a per-agent key.
"""

from __future__ import annotations


import jax.numpy as jnp

from lens_tpu.core.process import Process
from lens_tpu.ops.sampling import check_sampler, sample_poisson
from lens_tpu.processes import register
from lens_tpu.utils.rate_laws import first_order, hill_repression
from lens_tpu.utils.regulation_logic import compile_rule


def _count_leaf(default=0.0, emit=True):
    return {
        "_default": float(default),
        "_updater": "nonnegative_accumulate",
        "_divider": "binomial",
        "_emit": emit,
    }


class _MaybeStochastic(Process):
    """Shared ``sampler`` plumbing: ``None`` = deterministic mean-field;
    a sampler name flips ``self.stochastic`` (instance attribute shadows
    the class flag, so the engine starts passing a key) and routes each
    step's expected fluxes through ONE bulk Poisson draw."""

    def __init__(self, config=None):
        super().__init__(config)
        sampler = self.config.get("sampler")
        if sampler is not None:
            check_sampler(sampler)
            self.stochastic = True

    def _eventize(self, names, means, key):
        """{name: E[events]} -> {name: events}: stacked into one vector,
        one fused Poisson block, unpacked. Deterministic passthrough
        when ``sampler`` is None."""
        sampler = self.config.get("sampler")
        if sampler is None:
            return means
        lam = jnp.stack([jnp.maximum(means[n], 0.0) for n in names])
        events = sample_poisson(key, lam, sampler=sampler)
        return {n: events[i] for i, n in enumerate(names)}


@register
class Transcription(_MaybeStochastic):
    """Constitutive/regulated mRNA synthesis (counts/s per gene copy).

    ``rates``: transcript -> synthesis rate (counts/s).
    ``regulation``: transcript -> boolean rule string over species counts
    (e.g. ``"not repressor"``); when the rule evaluates False the gene is
    off. Smooth repression via ``repressors`` (Hill) is also supported for
    ODE-friendly dynamics. ``sampler``: see module docstring — discrete
    Poisson synthesis events instead of the mean-field flux.
    """

    name = "transcription"

    defaults = {
        "rates": {"mrna": 0.1},            # counts/s
        "regulation": {},                   # transcript -> rule string
        "repressors": {},                   # transcript -> (species, K, n)
        "sampler": None,                    # None | "hybrid" | "exact"
    }

    def __init__(self, config=None):
        super().__init__(config)
        self.transcripts = tuple(self.config["rates"])
        self._rules = {
            t: compile_rule(rule) for t, rule in self.config["regulation"].items()
        }
        # species referenced by any rule must appear in the ports schema
        self._rule_inputs = sorted(
            {dep for rule in self._rules.values() for dep in rule.names}
        )

    def ports_schema(self):
        counts = {t: _count_leaf() for t in self.transcripts}
        for species in self._rule_inputs:
            counts.setdefault(species, _count_leaf())
        for t, (species, _, _) in self.config["repressors"].items():
            counts.setdefault(species, _count_leaf())
        return {"counts": counts}

    def next_update(self, timestep, states, key=None):
        counts = states["counts"]
        update = {}
        for t in self.transcripts:
            rate = self.config["rates"][t]
            synthesis = rate * timestep
            if t in self._rules:
                on = self._rules[t](counts)
                synthesis = synthesis * on
            if t in self.config["repressors"]:
                species, k, n = self.config["repressors"][t]
                synthesis = synthesis * hill_repression(
                    counts[species], 1.0, k, n
                )
            update[t] = jnp.asarray(synthesis, jnp.float32)
        return {"counts": self._eventize(self.transcripts, update, key)}


@register
class Translation(_MaybeStochastic):
    """Protein synthesis proportional to transcript counts.

    ``pairs``: protein -> (mrna, rate) — each mRNA molecule produces
    ``rate`` proteins/s. ``sampler``: see module docstring.
    """

    name = "translation"

    defaults = {
        "pairs": {"protein": ("mrna", 0.05)},
        "sampler": None,                    # None | "hybrid" | "exact"
    }

    def ports_schema(self):
        counts = {}
        for protein, (mrna, _) in self.config["pairs"].items():
            counts[protein] = _count_leaf()
            counts.setdefault(mrna, _count_leaf())
        return {"counts": counts}

    def next_update(self, timestep, states, key=None):
        counts = states["counts"]
        proteins = tuple(self.config["pairs"])
        means = {
            protein: first_order(rate, counts[mrna]) * timestep
            for protein, (mrna, rate) in self.config["pairs"].items()
        }
        return {"counts": self._eventize(proteins, means, key)}


@register
class Degradation(_MaybeStochastic):
    """First-order decay of listed species: dN = -k * N * dt.

    ``sampler``: see module docstring — decay becomes discrete Poisson
    removal events, capped at the pool so a large-dt draw cannot remove
    molecules that are not there (the nonnegative updater would floor
    the POOL, but the cap keeps the event count itself honest).
    """

    name = "degradation"

    defaults = {
        "rates": {"mrna": 0.01, "protein": 0.0005},  # 1/s
        "sampler": None,                    # None | "hybrid" | "exact"
    }

    def ports_schema(self):
        return {"counts": {s: _count_leaf() for s in self.config["rates"]}}

    def next_update(self, timestep, states, key=None):
        counts = states["counts"]
        species = tuple(self.config["rates"])
        means = {
            s: first_order(k, counts[s]) * timestep
            for s, k in self.config["rates"].items()
        }
        events = self._eventize(species, means, key)
        if self.config.get("sampler") is not None:
            events = {
                s: jnp.minimum(events[s], jnp.maximum(counts[s], 0.0))
                for s in species
            }
        return {"counts": {s: -events[s] for s in species}}


@register
class Complexation(Process):
    """Stoichiometric complex formation/dissociation (mass action).

    ``reactions``: complex -> {"subunits": {species: stoich}, "k_on": rate,
    "k_off": rate}. Forward flux is mass-action in the subunit counts;
    reverse is first-order in the complex. Fluxes are clamped so no subunit
    pool goes negative within a step (the counts updater also guards, but
    clamping here keeps stoichiometric consistency between species).
    """

    name = "complexation"

    defaults = {
        "reactions": {
            "complex": {
                "subunits": {"monomer_a": 1, "monomer_b": 1},
                "k_on": 1e-3,
                "k_off": 1e-4,
            },
        },
    }

    def ports_schema(self):
        counts = {}
        for cplx, rxn in self.config["reactions"].items():
            counts[cplx] = _count_leaf()
            for species in rxn["subunits"]:
                counts.setdefault(species, _count_leaf())
        return {"counts": counts}

    def next_update(self, timestep, states):
        counts = states["counts"]
        reactions = self.config["reactions"]
        # 1. unclamped mass-action forward fluxes
        forwards = {}
        for cplx, rxn in reactions.items():
            forward = rxn["k_on"]
            for species, stoich in rxn["subunits"].items():
                forward = forward * jnp.maximum(counts[species], 0.0) ** stoich
            forwards[cplx] = forward * timestep
        # 2. joint clamp: reactions SHARING a subunit must not collectively
        # overdraw it (per-reaction clamping alone lets two reactions each
        # take the whole pool, and the nonnegative updater would then
        # fabricate complex mass). Scale every reaction by the tightest of
        # its subunits' availability ratios; total draw on species s is
        # then <= demand_s * (pool_s / demand_s) = pool_s.
        scales = {cplx: 1.0 for cplx in reactions}
        demand = {}
        for cplx, rxn in reactions.items():
            for species, stoich in rxn["subunits"].items():
                demand[species] = demand.get(species, 0.0) + stoich * forwards[cplx]
        for species, total in demand.items():
            pool = jnp.maximum(counts[species], 0.0)
            ratio = jnp.minimum(pool / jnp.maximum(total, 1e-30), 1.0)
            for cplx, rxn in reactions.items():
                if species in rxn["subunits"]:
                    scales[cplx] = jnp.minimum(scales[cplx], ratio)
        # 3. net fluxes and stoichiometric bookkeeping
        update = {s: 0.0 for s in self.ports_schema()["counts"]}
        for cplx, rxn in reactions.items():
            forward = forwards[cplx] * scales[cplx]
            # reverse is clamped to the complex pool for the same reason
            # the forwards are jointly clamped: an overshooting
            # dissociation would be floored at 0 by the updater while the
            # subunits were credited the full amount — fabricating mass
            pool = jnp.maximum(counts[cplx], 0.0)
            reverse = jnp.minimum(
                first_order(rxn["k_off"], counts[cplx]) * timestep, pool
            )
            net = forward - reverse
            update[cplx] = update[cplx] + net
            for species, stoich in rxn["subunits"].items():
                update[species] = update[species] - stoich * net
        return {"counts": update}
