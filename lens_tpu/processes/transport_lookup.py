"""Table-lookup kinetic transport: precomputed flux surfaces.

The reference ships a transport Process that replaces live kinetics with a
lookup into precomputed flux surfaces — flux as a function of external
substrate and internal state, tabulated offline (reconstructed:
``lens/processes/transport_lookup.py``, SURVEY.md §2 "Transport-lookup
process", confidence C). On TPU this pattern is if anything MORE natural
than on CPU: a bilinear interpolation over a static grid is a handful of
gathers + fused FMAs, with no data-dependent control flow, and the table
lives in HBM once for all 100k agents.

``flux_table`` is a [n_ext, n_int] grid of net uptake rates (mM/s,
positive = uptake) sampled at ``ext_grid`` x ``int_grid`` axis points;
queries clamp to the table edges (constant extrapolation).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lens_tpu.core.process import Process
from lens_tpu.processes import register
from lens_tpu.utils.rate_laws import michaelis_menten


def _default_table():
    """A MM-with-product-inhibition surface, tabulated — stands in for the
    reference's offline-fitted surfaces until real data is loaded."""
    ext = np.linspace(0.0, 20.0, 32, dtype=np.float32)     # mM external
    internal = np.linspace(0.0, 10.0, 16, dtype=np.float32)  # mM internal
    e, i = np.meshgrid(ext, internal, indexing="ij")
    flux = np.asarray(michaelis_menten(e, 0.1, 0.5)) / (1.0 + i / 5.0)
    return ext, internal, flux.astype(np.float32)


def bilinear_lookup(table, x_grid, y_grid, x, y):
    """Bilinear interpolation on a 2D grid with edge clamping. Pure jnp."""
    x = jnp.clip(x, x_grid[0], x_grid[-1])
    y = jnp.clip(y, y_grid[0], y_grid[-1])
    ix = jnp.clip(jnp.searchsorted(x_grid, x) - 1, 0, x_grid.shape[0] - 2)
    iy = jnp.clip(jnp.searchsorted(y_grid, y) - 1, 0, y_grid.shape[0] - 2)
    x0, x1 = x_grid[ix], x_grid[ix + 1]
    y0, y1 = y_grid[iy], y_grid[iy + 1]
    tx = (x - x0) / jnp.maximum(x1 - x0, 1e-12)
    ty = (y - y0) / jnp.maximum(y1 - y0, 1e-12)
    f00 = table[ix, iy]
    f01 = table[ix, iy + 1]
    f10 = table[ix + 1, iy]
    f11 = table[ix + 1, iy + 1]
    return (
        f00 * (1 - tx) * (1 - ty)
        + f10 * tx * (1 - ty)
        + f01 * (1 - tx) * ty
        + f11 * tx * ty
    )


@register
class TransportLookup(Process):
    """Spatially-coupled transport whose rate comes from a flux table.

    Same port conventions as MichaelisMentenTransport (``external`` is
    wrapper-owned, ``exchange`` accumulates net secretion), but the uptake
    rate is ``bilinear_lookup(flux_table, ext_grid, int_grid, s_ext,
    s_int)`` instead of a closed-form rate law.
    """

    name = "transport_lookup"

    defaults = {
        "molecule": "glucose",
        "ext_grid": None,     # [n_ext] axis, mM external
        "int_grid": None,     # [n_int] axis, mM internal
        "flux_table": None,   # [n_ext, n_int] net uptake, mM/s
        "k_consume": 0.05,    # 1/s drain of the internal pool
    }

    def __init__(self, config=None):
        super().__init__(config)
        table_keys = ("ext_grid", "int_grid", "flux_table")
        given = [k for k in table_keys if self.config[k] is not None]
        if not given:
            ext, internal, table = _default_table()
            self.ext_grid = jnp.asarray(ext)
            self.int_grid = jnp.asarray(internal)
            self.flux_table = jnp.asarray(table)
        elif len(given) == len(table_keys):
            self.ext_grid = jnp.asarray(self.config["ext_grid"])
            self.int_grid = jnp.asarray(self.config["int_grid"])
            self.flux_table = jnp.asarray(self.config["flux_table"])
        else:
            missing = sorted(set(table_keys) - set(given))
            raise ValueError(
                f"TransportLookup needs all of {table_keys} together "
                f"(got {given}, missing {missing}) — a partial table "
                f"spec would silently fall back to the built-in demo surface"
            )
        expected = (self.ext_grid.shape[0], self.int_grid.shape[0])
        if self.flux_table.shape != expected:
            raise ValueError(
                f"flux_table shape {self.flux_table.shape} != grid shape {expected}"
            )

    def ports_schema(self):
        mol = self.config["molecule"]
        return {
            "external": {
                mol: {"_default": 10.0, "_updater": "null", "_divider": "copy"},
            },
            "internal": {
                f"{mol}_internal": {
                    "_default": 0.0,
                    "_updater": "nonnegative_accumulate",
                    "_divider": "split",
                },
            },
            "exchange": {
                f"{mol}_exchange": {
                    "_default": 0.0,
                    "_updater": "accumulate",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
        }

    def next_update(self, timestep, states):
        mol = self.config["molecule"]
        s_ext = states["external"][mol]
        pool = states["internal"][f"{mol}_internal"]
        rate = bilinear_lookup(
            self.flux_table, self.ext_grid, self.int_grid, s_ext, pool
        )
        uptake = jnp.minimum(rate * timestep, jnp.maximum(s_ext, 0.0))
        return {
            "internal": {
                f"{mol}_internal": uptake
                - self.config["k_consume"] * pool * timestep
            },
            "exchange": {f"{mol}_exchange": -uptake},
        }
