"""Deriver processes: non-mechanistic bookkeeping after each engine step.

The reference runs small "derive" processes that keep dependent quantities
consistent — volume from mass, concentrations from counts, the division
condition (reconstructed: ``lens/processes/derive_*.py``, SURVEY.md §2
"Derivers"). Derivers subclass :class:`lens_tpu.core.process.Deriver`, so
the engine runs them after the mechanistic merge, in registration order
(``Compartment.step``), each seeing the already-merged state.

All leaves they own are ``_updater: set`` — derived state is overwritten,
never accumulated.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from lens_tpu.core.process import Deriver, Process
from lens_tpu.processes import register
from lens_tpu.utils.units import (
    CELL_DENSITY_FG_PER_FL,
    counts_to_millimolar,
    volume_from_mass,
)


@register
class DeriveVolume(Deriver):
    """volume (fL) = mass (fg) / density — constant-density geometry.

    Pairs with a mass-accumulating growth process: mechanistic processes
    add mass; this deriver keeps volume consistent so concentration-based
    kinetics and the division trigger see up-to-date geometry.
    """

    name = "derive_volume"
    defaults = {"density": CELL_DENSITY_FG_PER_FL}  # fg / fL

    def ports_schema(self):
        # mass is read-only here but its declaration must match the growth
        # process's (shared-variable declarations must agree)
        return {
            "global": {
                "mass": {
                    "_default": 330.0,
                    "_updater": "accumulate",
                    "_divider": "split",
                },
                "volume": {
                    "_default": 1.0,
                    "_updater": "set",
                    "_divider": "split",
                },
            },
        }

    def next_update(self, timestep, states):
        mass = states["global"]["mass"]
        return {
            "global": {"volume": volume_from_mass(mass, self.config["density"])}
        }


@register
class DeriveConcentrations(Deriver):
    """concentrations (mM) = counts / (N_A * volume) for listed molecules.

    The bridge between discrete-count processes (stochastic expression,
    complexation) and concentration-based kinetics (transport, metabolism):
    counts live in a ``counts`` store, this deriver maintains a parallel
    ``concentrations`` store.
    """

    name = "derive_concentrations"
    defaults = {"molecules": ("protein",)}

    def __init__(self, config=None):
        super().__init__(config)
        self.molecules: Sequence[str] = tuple(self.config["molecules"])

    def ports_schema(self):
        # counts/volume are read-only here; declarations mirror the
        # expression processes' count convention and DeriveVolume's volume
        # so shared-path declarations agree in composites.
        schema = {
            "counts": {
                mol: {
                    "_default": 0.0,
                    "_updater": "nonnegative_accumulate",
                    "_divider": "binomial",
                }
                for mol in self.molecules
            },
            "global": {
                "volume": {"_default": 1.0, "_updater": "set", "_divider": "split"},
            },
            "concentrations": {
                mol: {"_default": 0.0, "_updater": "set", "_divider": "copy"}
                for mol in self.molecules
            },
        }
        return schema

    def next_update(self, timestep, states):
        volume = states["global"]["volume"]
        return {
            "concentrations": {
                mol: counts_to_millimolar(states["counts"][mol], volume)
                for mol in self.molecules
            }
        }


@register
class DivideCondition(Deriver):
    """Division condition on an arbitrary global variable (mass or volume).

    Generalizes ``DivideTrigger`` (volume-doubling) to any watched
    variable/threshold — the reference's division deriver pattern
    (SURVEY.md §3.3: "division deriver sets trigger (e.g. volume >= 2x)").
    The colony layer watches the ``divide`` flag for row activation.
    """

    name = "divide_condition"
    #: ``updater``/``divider`` declare how the WATCHED variable merges —
    #: they must mirror the declaration of whichever process owns it
    #: (e.g. ``updater="set"`` when watching DeriveVolume's volume),
    #: since shared-path declarations must agree across processes.
    defaults = {
        "variable": "mass",
        "threshold": 660.0,
        "default": 330.0,
        "updater": "accumulate",
        "divider": "split",
    }

    def ports_schema(self):
        var = self.config["variable"]
        return {
            "global": {
                var: {
                    "_default": float(self.config["default"]),
                    "_updater": self.config["updater"],
                    "_divider": self.config["divider"],
                },
                "divide": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
        }

    def next_update(self, timestep, states):
        value = states["global"][self.config["variable"]]
        return {
            "global": {
                "divide": (value >= self.config["threshold"]).astype(jnp.float32)
            }
        }


@register
class MassGrowth(Process):
    """Exponential dry-mass growth (mechanistic counterpart of DeriveVolume).

    Composites that track mass grow it here, then DeriveVolume keeps the
    geometry consistent: m += m * (exp(r dt) - 1).
    """

    name = "mass_growth"
    defaults = {"rate": 0.0005}  # 1/s

    def ports_schema(self):
        return {
            "global": {
                "mass": {
                    "_default": 330.0,
                    "_updater": "accumulate",
                    "_divider": "split",
                },
            },
        }

    def next_update(self, timestep, states):
        m = states["global"]["mass"]
        return {
            "global": {"mass": m * (jnp.exp(self.config["rate"] * timestep) - 1.0)}
        }
