"""Genome-scale stochastic expression: a gene TABLE, one tau-leap.

:mod:`~lens_tpu.processes.stochastic_expression` steps ONE gene; the
reference's expression layer is a whole regulated gene complement driven
from its flat-file knowledge base (reconstructed: SURVEY.md §2 "Gene
expression processes" + "Data layer"). This process closes that scale
gap the TPU way: all G genes' (mRNA, protein) counts are two ``[G]``
vector leaves stepped by ONE tau-leap over a block-diagonal 4G-reaction
network — per-agent cost is a fixed [4G, 2G] matmul, which ``vmap``
batches across the colony onto the MXU.

Regulation couples transcription to the environment: each gene may carry
a boolean rule over EXTERNAL species (``utils.regulation_logic``, same
grammar as the rFBA reaction rules), and a false rule gates that gene's
transcription propensity to zero — the lac operon reads the same
glucose/lactose fields the metabolism LP does.

Gene complement comes from the data layer: ``genes="ecoli_core"`` loads
``data/ecoli_core_genes.tsv`` (32 genes, the enzymes of the ecoli_core
rFBA network). Rates are schema state (``_updater: null``), so per-agent
overrides still work as in the one-gene process.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from lens_tpu.core.process import Process
from lens_tpu.ops.gillespie import tau_leap_window
from lens_tpu.ops.sampling import check_sampler, check_threshold
from lens_tpu.processes import register
from lens_tpu.utils.regulation_logic import compile_rule

#: per-gene reaction block [4, 2]; species order (mRNA, protein)
_BLOCK = np.asarray(
    [
        [1.0, 0.0],   # transcription
        [0.0, 1.0],   # translation
        [-1.0, 0.0],  # mRNA decay
        [0.0, -1.0],  # protein decay
    ],
    np.float32,
)


@register
class GenomeExpression(Process):
    name = "genome_expression"
    stochastic = True

    defaults = {
        # name of a packaged gene table ("ecoli_core") or a list of row
        # dicts with keys gene/k_tx/k_tl/d_m/d_p and optional rule.
        "genes": "ecoli_core",
        "substeps": 10,
        # Poisson event sampler (ops.sampling): "hybrid" = the batched
        # quantile-transform fast path, one fused [substeps, 4G] uniform
        # block per agent per step; "exact" = jax.random.poisson,
        # bitwise-compatible with pre-fast-path checkpoints.
        "sampler": "hybrid",
        "sampler_threshold": 10.0,
        "regulation_threshold": 0.05,  # presence threshold for rules
        # Schema default for external species read by rules; shared-path
        # declarations must agree across processes (core.engine), so wire
        # this to match co-wired transport/metabolism processes.
        "external_defaults": {},
    }

    def __init__(self, config=None):
        super().__init__(config)
        check_sampler(self.config["sampler"])  # typo -> fail at build
        check_threshold(self.config["sampler_threshold"])
        genes = self.config["genes"]
        if isinstance(genes, str):
            from lens_tpu.data import load_tsv

            genes = load_tsv(f"{genes}_genes.tsv")
        self.genes: List[str] = [str(row["gene"]) for row in genes]
        if len(self.genes) != len(set(self.genes)):
            raise ValueError("duplicate gene names in the gene table")
        g = len(self.genes)

        def col(key):
            return np.asarray([float(row[key]) for row in genes], np.float32)

        self._k_tx = col("k_tx")
        self._k_tl = col("k_tl")
        self._d_m = col("d_m")
        self._d_p = col("d_p")
        self._rules: Dict[int, Any] = {}
        rule_species: List[str] = []
        for i, row in enumerate(genes):
            rule = row.get("rule") or ""
            if rule:
                compiled = compile_rule(
                    str(rule), threshold=self.config["regulation_threshold"]
                )
                self._rules[i] = compiled
                rule_species.extend(compiled.names)
        self.rule_species: List[str] = sorted(set(rule_species))
        # block-diagonal genome stoichiometry [4G, 2G]
        self._stoich = jnp.asarray(np.kron(np.eye(g, dtype=np.float32), _BLOCK))

    # -- declarative surface -------------------------------------------------

    def ports_schema(self):
        g = len(self.genes)
        count = {
            "_default": np.zeros(g, np.float32),
            "_updater": "nonnegative_accumulate",
            "_divider": "binomial",
        }
        rate = lambda v: {
            "_default": v,
            "_updater": "null",
            "_divider": "copy",
            "_emit": False,
        }
        schema = {
            "counts": {"mrna": dict(count), "protein": dict(count)},
            "rates": {
                "k_tx": rate(self._k_tx),
                "k_tl": rate(self._k_tl),
                "d_m": rate(self._d_m),
                "d_p": rate(self._d_p),
            },
        }
        if self.rule_species:
            defaults = self.config["external_defaults"]
            schema["external"] = {
                mol: {
                    "_default": float(defaults.get(mol, 0.0)),
                    "_updater": "null",
                    "_divider": "copy",
                }
                for mol in self.rule_species
            }
        return schema

    # -- dynamics ------------------------------------------------------------

    def next_update(self, timestep, states, key=None):
        g = len(self.genes)
        m = states["counts"]["mrna"]
        p = states["counts"]["protein"]
        r = states["rates"]

        gate = jnp.ones(g, m.dtype)
        if self._rules:
            env = {mol: states["external"][mol] for mol in self.rule_species}
            for i, rule in self._rules.items():
                gate = gate.at[i].set(rule(env))

        counts = jnp.stack([m, p], axis=1).reshape(2 * g)  # [2G] interleaved

        def propensities(x):
            xm = x.reshape(g, 2)
            props = jnp.stack(
                [
                    r["k_tx"] * gate,
                    r["k_tl"] * xm[:, 0],
                    r["d_m"] * xm[:, 0],
                    r["d_p"] * xm[:, 1],
                ],
                axis=1,
            )  # [G, 4]
            return props.reshape(4 * g)

        new = tau_leap_window(
            key, counts, self._stoich, propensities, timestep,
            int(self.config["substeps"]),
            sampler=self.config["sampler"],
            threshold=float(self.config["sampler_threshold"]),
        ).reshape(g, 2)
        return {
            "counts": {
                "mrna": new[:, 0] - m,
                "protein": new[:, 1] - p,
            },
        }
