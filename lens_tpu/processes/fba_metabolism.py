"""Exact flux-balance metabolism with boolean regulation (rFBA).

The reference's metabolism Process descends from Covert–Palsson 2002
regulated FBA: optimize growth over a stoichiometric network each step,
with boolean transcriptional rules switching reactions on/off
(reconstructed: ``lens/processes/…metabolism….py``, SURVEY.md §2
"Metabolism process"). :mod:`lens_tpu.processes.metabolism` is the kinetic
v1 stand-in; THIS module is the exact-LP version SURVEY.md §7 ranked the
hardest gap, made TPU-native by :func:`lens_tpu.ops.linprog.flux_balance`
— a fixed-iteration interior-point solve that ``vmap``s across the colony
(one batched [N, M, M] Cholesky pipeline on the MXU instead of N simplex
tableaus).

Per agent per step:

1. **Bounds from the environment**: each exchange reaction's uptake bound
   follows Michaelis–Menten saturation of the local external
   concentration (so starved cells cannot import what is not there).
2. **Regulation**: each rule (compiled once by
   ``utils.regulation_logic``) evaluates on EXTERNAL species — internal
   metabolites are steady-state LP rows, not pools, so they carry no
   concentration a rule could read; a false rule clamps its reaction's
   bounds to zero. This is the rFBA
   two-layer loop: metabolism moves species, species flip rules, rules
   reshape tomorrow's feasible flux cone.
3. **LP**: maximize biomass flux subject to steady-state internal
   metabolites and the regulated bounds.
4. **Apply**: exchange fluxes accumulate into the ``exchange`` port
   (spatial wrapper scatters them into lattice fields), biomass flux
   grows ``mass``, and flux telemetry lands in an emit-only port.

The default network is a deliberately small core-carbon skeleton in the
shape Covert–Palsson used: glucose and acetate routes into a carbon
intermediate, respiration vs fermentation (overflow) branches for ATP,
catabolite repression of acetate uptake, and oxygen gating of
respiration — enough structure to reproduce diauxic growth and
aerobic/anaerobic shifts, the phenomena the reference's regulated model
exists to show.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.core.process import Process
from lens_tpu.ops.linprog import (
    flux_balance,
    pack_warm,
    unpack_warm,
    warm_size,
)
from lens_tpu.processes import register
from lens_tpu.utils.regulation_logic import compile_rule

#: Core-carbon skeleton network. Internal species (steady-state LP rows):
#: C (carbon intermediate), ATP, NADH. External species (lattice fields /
#: ``external`` port): glc, ace, o2. Fluxes in mM/s; bounds are
#: (lb, ub); ``exchange`` names the external species a reaction imports
#: (+1 flux = 1 unit taken up from the environment).
CORE_RFBA_NETWORK = {
    "internal": ["C", "ATP", "NADH"],
    "external": ["glc", "ace", "o2"],
    "reactions": {
        # Transport (import): external -> internal carbon.
        "glc_uptake": {
            "stoich": {"C": 2.0},
            "bounds": (0.0, 1.0),
            "exchange": "glc",
            "km": 0.5,
            "rule": "",
        },
        "ace_uptake": {
            "stoich": {"C": 1.0},
            "bounds": (0.0, 0.8),
            "exchange": "ace",
            "km": 1.0,
            # Catabolite repression: acetate route transcribed only when
            # glucose is absent (the diauxie switch).
            "rule": "not glc",
        },
        # Respiratory capacity is deliberately BELOW what full glucose
        # influx needs — that bound binding is what produces overflow
        # acetate secretion at high glucose (the Crabtree-like phenotype
        # the regulated core model reproduces).
        "o2_uptake": {
            "stoich": {"NADH": -2.0},   # respiration re-oxidizes NADH
            "bounds": (0.0, 0.8),
            "exchange": "o2",
            "km": 0.2,
            "rule": "",
        },
        # Catabolism: C -> energy carriers.
        "oxidation": {
            "stoich": {"C": -1.0, "ATP": 2.0, "NADH": 2.0},
            "bounds": (0.0, 4.0),
            "rule": "",
        },
        # Overflow/fermentation: C -> acetate (secreted) + a little ATP;
        # the only NADH-neutral ATP source, so it carries anaerobic growth.
        "fermentation": {
            "stoich": {"C": -1.0, "ATP": 1.0},
            "bounds": (0.0, 4.0),
            "exchange": "ace",
            "exchange_stoich": -1.0,    # secretes 1 ace per unit flux
            "rule": "",
        },
        # Growth: carbon + ATP -> biomass (the objective).
        "biomass": {
            "stoich": {"C": -1.0, "ATP": -2.5},
            "bounds": (0.0, 2.0),
            "rule": "",
        },
        # Non-growth maintenance: a fixed ATP drain (lb == ub > 0).
        "maintenance": {
            "stoich": {"ATP": -1.0},
            "bounds": (0.05, 0.05),
            "rule": "",
        },
    },
    "objective": "biomass",
}


@register
class FBAMetabolism(Process):
    """Regulated flux-balance metabolism (exact LP per agent per step).

    Ports (spatial-coupling conventions of
    :class:`~lens_tpu.processes.mm_transport.MichaelisMentenTransport`):

    - ``external``: local lattice concentrations of the network's external
      species (``_updater: null`` — written by the spatial wrapper).
    - ``exchange``: accumulated net secretion per external species
      (negative = uptake), zeroed by the wrapper after scatter.
    - ``global``: ``mass`` (fg) grown from biomass flux.
    - ``fluxes``: emit-only LP telemetry (solution fluxes, convergence).
    """

    name = "fba_metabolism"

    defaults = {
        # A network dict (CORE_RFBA_NETWORK's shape) or the name of a
        # packaged network loaded via data.load_rfba_network: the default
        # "core_skeleton" is the data-layer form of CORE_RFBA_NETWORK
        # (equivalence pinned by tests); "ecoli_core" is the
        # 24-metabolite x 35-reaction Covert–Palsson-style network in
        # lens_tpu/data/ecoli_core_reactions.tsv.
        "network": "core_skeleton",
        # fg mass per unit biomass flux·s. Calibration: aerobic glucose
        # growth solves at v_bio ~ 0.8, so dm/dt ~ 0.24 fg/s doubles a
        # 330 fg cell in ~1400 s — the E. coli-ish ~23 min doubling the
        # kinetic Growth process also targets.
        "mass_yield": 0.3,
        "regulation_threshold": 0.05,  # mM presence threshold for rules
        # CAP on IPM iterations, not a fixed count: the solve exits as
        # soon as the whole vmapped batch has frozen (typically ~10
        # iterations; the cap covers regulation-degenerate corners).
        "lp_iterations": 30,
        "lp_tol": 1e-5,
        # Steady-state leak relaxation (ops.linprog.flux_balance): 0 keeps
        # S v = 0 exact — fine for small networks. Reference-scale
        # regulated networks NEED ~1.5e-3 for the float32 solve to stay
        # conditioned when regulation gates whole metabolite rows (see
        # flux_balance docstring); pair with lp_tol=1e-4, lp_iterations
        # ~60 (what the `rfba_lattice` composite sets for "ecoli_core").
        "lp_leak": 0.0,
        # Exchange accounting happens in environment units; uptake is also
        # capped so one window cannot import more than is locally present.
        "uptake_cap_fraction": 0.9,
        # Warm-start each step's LP from the previous step's IPM iterate
        # (ops.linprog.WarmStart): environments change slowly, so temporal
        # coherence cuts iterations — and under vmap the batch runs as
        # long as its SLOWEST lane, so fewer iterations per lane is a
        # direct wall-clock win. Adds a small non-emitted "lp_state" port;
        # a hint only (acceptance tests are unchanged), dropped
        # automatically when the solve fails or the port is not wired.
        "lp_warm_start": True,
        # Which batched LP engine solves the per-agent FBA:
        # - "ipm" (default): the dense Mehrotra interior-point method
        #   (ops.linprog) — O(M^2 R + M^3/3) per iteration, ~10
        #   iterations; the right tool through reference scale (72x180).
        # - "pdlp": the first-order restarted PDHG (ops.pdlp) — O(M R)
        #   matvecs per iteration, thousands of iterations; the scaling
        #   path for networks past the dense-Cholesky crossover
        #   (bench_lp_scale.py records where that is). Warm-state layout
        #   differs, so a checkpoint taken with one solver does not
        #   resume with the other.
        "lp_solver": "ipm",
        # Iteration CAP for the pdlp solver only (its iterations are
        # matvec-cheap; the cap covers cold starts — warm-started steps
        # exit far earlier). Sized ABOVE the measured cold-start
        # envelope (13k-25k iterations on the tiled-network sweep,
        # BENCH_LP_SCALE_CPU_r05.json): an undersized cap is sticky —
        # a failed solve leaves warm.flag = 0, so the next step repeats
        # the same doomed cold solve and the agent silently never grows.
        "pdlp_iterations": 32768,
    }

    def __init__(self, config=None):
        super().__init__(config)
        net = self.config["network"]
        if isinstance(net, str):
            from lens_tpu.data import load_rfba_network

            net = load_rfba_network(net)
        self.internal: Tuple[str, ...] = tuple(net["internal"])
        self.external: Tuple[str, ...] = tuple(net["external"])
        self.reactions: Tuple[str, ...] = tuple(net["reactions"])
        n_r = len(self.reactions)
        n_m = len(self.internal)
        i_index = {s: i for i, s in enumerate(self.internal)}

        stoich = np.zeros((n_m, n_r), np.float32)
        lb = np.zeros(n_r, np.float32)
        ub = np.zeros(n_r, np.float32)
        objective = np.zeros(n_r, np.float32)
        # Exchange matrix: [n_external, n_reactions]; +1 = imports one unit
        # of that external species per unit flux, -1 = secretes.
        exchange = np.zeros((len(self.external), n_r), np.float32)
        kms = np.zeros(n_r, np.float32)
        uptake_mask = np.zeros(n_r, bool)
        self._rules: Dict[int, object] = {}

        for j, name in enumerate(self.reactions):
            rxn = net["reactions"][name]
            for s, coeff in rxn["stoich"].items():
                stoich[i_index[s], j] = coeff
            lb[j], ub[j] = rxn["bounds"]
            # Exchange coupling: either an `exchanges` dict (the data-layer
            # form; several species per reaction, fractional coefficients
            # like o2:0.5 for lumped oxphos) or the legacy single
            # `exchange` + `exchange_stoich` pair.
            pairs = dict(rxn.get("exchanges") or {})
            mol = rxn.get("exchange")
            if mol is not None:
                pairs[mol] = rxn.get("exchange_stoich", 1.0)
            for mol, coeff in pairs.items():
                e = self.external.index(mol)
                exchange[e, j] = coeff
                if coeff > 0:  # an import: env-limited
                    uptake_mask[j] = True
                    # km=0 is meaningful (disables MM saturation):
                    # honor an explicit zero, default only a MISSING key
                    kms[j] = rxn.get("km", 0.5)
            rule = rxn.get("rule", "")
            if rule:
                self._rules[j] = compile_rule(
                    rule, threshold=self.config["regulation_threshold"]
                )
        # Rules can only read EXTERNAL species: internal metabolites are
        # steady-state LP rows with no concentration to evaluate. Reject
        # at construction, not as a KeyError mid-trace.
        for r in self._rules.values():
            bad = [n for n in r.names if n not in self.external]
            if bad:
                raise ValueError(
                    f"rule {r.source!r} references {bad}: regulation rules "
                    f"may only read external species {list(self.external)} "
                    f"(internal metabolites are steady-state, they have no "
                    f"concentration)"
                )

        self.stoichiometry = jnp.asarray(stoich)     # [M, R]
        self.lb = jnp.asarray(lb)
        self.ub = jnp.asarray(ub)
        self.objective = jnp.asarray(objective)
        self.objective = self.objective.at[
            self.reactions.index(net["objective"])
        ].set(1.0)
        self.exchange_matrix = jnp.asarray(exchange)  # [E, R]
        self.kms = jnp.asarray(kms)
        self.uptake_mask = jnp.asarray(uptake_mask)
        self.biomass_index = self.reactions.index(net["objective"])
        # Availability-cap bookkeeping: the cap must bound the SUMMED
        # uptake per external species, so each import reaction gets an
        # equal share of its species' availability (two importers of one
        # species may not jointly overdraw the bin — the lattice's >=0
        # clamp would otherwise create mass).
        pos = np.clip(exchange, 0.0, None)               # [E, R]
        self._import_indicator = jnp.asarray((pos > 0).astype(np.float32))
        self._import_coeff = jnp.asarray(
            np.maximum(pos.sum(axis=0), 1e-12), jnp.float32
        )  # [R] units of species imported per unit flux
        # (the per-step active-importer share is computed in next_update,
        # after regulation gates are known)
        # Warm-start bookkeeping: the LP's column space includes the leak
        # slack columns flux_balance appends, so the packed vector is
        # sized for the FULL problem.
        n_lp_vars = n_r + (n_m if self.config["lp_leak"] > 0.0 else 0)
        self._n_lp_vars = n_lp_vars
        solver = self.config["lp_solver"]
        if solver not in ("ipm", "pdlp"):
            raise ValueError(
                f"lp_solver must be 'ipm' or 'pdlp', got {solver!r}"
            )
        if solver == "pdlp":
            from lens_tpu.ops.pdlp import warm_size_pdlp

            self._warm_len = warm_size_pdlp(n_m, n_lp_vars)
        else:
            self._warm_len = warm_size(n_m, n_lp_vars)

    # -- declarative surface --------------------------------------------------

    def ports_schema(self):
        n_r = len(self.reactions)
        schema = {
            "lp_state": {
                "warm": {
                    # Packed ops.linprog.WarmStart: the previous step's
                    # interior-point iterate. "copy" divider: daughters
                    # inherit the mother's basis (their environment is
                    # hers to first order).
                    "_default": jnp.zeros(self._warm_len, jnp.float32),
                    "_updater": "set",
                    "_divider": "copy",
                    "_emit": False,
                },
            },
        } if self.config["lp_warm_start"] else {}
        return schema | {
            "external": {
                mol: {"_default": 10.0, "_updater": "null", "_divider": "copy"}
                for mol in self.external
            },
            "exchange": {
                f"{mol}_exchange": {
                    "_default": 0.0,
                    "_updater": "accumulate",
                    "_divider": "zero",
                    "_emit": False,
                }
                for mol in self.external
            },
            "global": {
                "mass": {
                    "_default": 330.0,
                    "_updater": "accumulate",
                    "_divider": "split",
                },
            },
            "fluxes": {
                "reaction_fluxes": {
                    "_default": jnp.zeros(n_r, jnp.float32),
                    "_updater": "set",
                    "_divider": "copy",
                },
                "growth_rate": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "copy",
                },
                "lp_converged": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "copy",
                },
                "lp_iterations": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "copy",
                },
            },
        }

    # -- dynamics -------------------------------------------------------------

    def regulated_bounds(self, ext, timestep):
        """The per-agent LP box: regulation gates + environment limits.

        ``ext``: [n_external] local concentrations in ``self.external``
        order. Returns ``(lb, ub)`` — exactly the bounds ``next_update``
        hands the LP, exposed so oracle tests can re-solve the identical
        problem.
        """
        # 1. Boolean regulation gates, computed first: the availability cap
        # below splits each species among its ACTIVE importers only.
        env = {mol: ext[e] for e, mol in enumerate(self.external)}
        gate = jnp.ones(len(self.reactions), ext.dtype)
        for j, rule in self._rules.items():
            gate = gate.at[j].set(rule(env))

        # 2. Environment-dependent uptake bounds: MM saturation on the raw
        # species concentration (Km is in concentration units), plus a hard
        # cap so dt * SUMMED uptake per species never exceeds the locally
        # available amount — each active importer gets an equal share.
        # Default network: one importer per species, coeff 1 — identical to
        # a per-reaction cap. (A reaction importing SEVERAL species — none
        # packaged — would saturate on their summed concentration.)
        ext_of_rxn = self._import_indicator.T @ ext  # [R] raw species conc
        saturation = ext_of_rxn / (self.kms + ext_of_rxn + 1e-12)
        active = gate * self.uptake_mask                       # [R]
        share = jnp.maximum(
            self._import_indicator.T @ (self._import_indicator @ active), 1.0
        )  # [R] active importers of this reaction's species
        avail_cap = (
            self.config["uptake_cap_fraction"]
            * ext_of_rxn
            / (self._import_coeff * share * timestep)
        )
        ub = jnp.where(
            self.uptake_mask,
            jnp.minimum(self.ub * saturation, avail_cap),
            self.ub,
        )
        lb = jnp.where(self.uptake_mask, jnp.zeros_like(self.lb), self.lb)
        lb = jnp.minimum(lb, ub)  # keep the box consistent under capping

        # 3. Regulation clamps both bounds of gated reactions.
        lb = lb * gate
        ub = ub * gate
        return lb, ub

    def next_update(self, timestep, states):
        # f32 matmuls throughout: bf16 (the TPU default) exchange/bound
        # arithmetic would leak ~0.4% of every flux, breaking the
        # lattice mass-conservation contract (and the LP itself needs it
        # — see ops.linprog).
        with jax.default_matmul_precision("float32"):
            return self._next_update(timestep, states)

    def _next_update(self, timestep, states):
        ext = jnp.stack([states["external"][mol] for mol in self.external])
        lb, ub = self.regulated_bounds(ext, timestep)

        # 4. The LP: max biomass s.t. S v = 0 (to lp_leak), lb <= v <= ub,
        # warm-started from the previous step's iterate when the lp_state
        # port is wired (tests that hand-build states without it fall back
        # to the cold start — identical answers, more iterations).
        pdlp = self.config["lp_solver"] == "pdlp"
        if pdlp:
            from lens_tpu.ops.pdlp import (
                flux_balance_pdlp,
                pack_warm_pdlp,
                unpack_warm_pdlp,
            )
        warm = None
        if self.config["lp_warm_start"] and "lp_state" in states:
            unpack = unpack_warm_pdlp if pdlp else unpack_warm
            warm = unpack(
                states["lp_state"]["warm"],
                len(self.internal),
                self._n_lp_vars,
            )
        solve = flux_balance_pdlp if pdlp else flux_balance
        sol = solve(
            self.stoichiometry,
            self.objective,
            lb,
            ub,
            n_iter=(
                self.config["pdlp_iterations"]
                if pdlp
                else self.config["lp_iterations"]
            ),
            tol=self.config["lp_tol"],
            leak=self.config["lp_leak"],
            warm=warm,
        )
        # A failed solve (infeasible bounds — e.g. maintenance cannot be
        # met) means no growth and no exchange, not garbage fluxes.
        ok = sol.converged
        v = jnp.where(ok, sol.x, jnp.zeros_like(sol.x))

        # 5. Deltas. Exchange port counts net secretion (negative=uptake).
        net_uptake = self.exchange_matrix @ v          # [E], + = imported
        growth = v[self.biomass_index]
        update = {} if warm is None else {
            "lp_state": {
                "warm": (pack_warm_pdlp if pdlp else pack_warm)(sol.warm)
            }
        }
        return update | {
            "exchange": {
                f"{mol}_exchange": -net_uptake[e] * timestep
                for e, mol in enumerate(self.external)
            },
            "global": {
                "mass": self.config["mass_yield"] * growth * timestep
            },
            "fluxes": {
                "reaction_fluxes": v,
                "growth_rate": growth,
                "lp_converged": ok.astype(jnp.float32),
                # Solver iterations before this agent's solve froze —
                # IPM Newton steps (cap: config "lp_iterations") or,
                # under lp_solver="pdlp", PDHG iterations quantized to
                # restart windows (cap: "pdlp_iterations"). Emitted so a
                # creeping network/conditioning problem shows up as
                # rising iteration counts long before convergence
                # failures do.
                "lp_iterations": sol.iterations.astype(jnp.float32),
            },
        }
