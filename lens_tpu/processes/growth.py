"""Growth and division-trigger processes.

The reference pairs a mass-accumulation growth process with a division
deriver that trips when the cell doubles (reconstructed:
``lens/processes/``, derivers in SURVEY.md §2 "Division/growth"). Here
growth is exponential in volume and the trigger is a plain schema variable
the colony layer watches (``Colony(division_trigger=...)``) — division
itself is row activation, not a handshake.
"""

from __future__ import annotations

import jax.numpy as jnp

from lens_tpu.core.process import Deriver, Process
from lens_tpu.processes import register


@register
class Growth(Process):
    """Exponential volume growth: V(t+dt) = V(t) * exp(rate * dt).

    ``per_agent_rates: True`` promotes the rate to a per-agent schema
    variable ``global/growth_rate`` (default = the config ``rate``; seed
    a spread via ``initial_state`` overrides). Daughters INHERIT the
    parent's rate (``_divider: copy``), so lineages carry their growth
    phenotype — the classic heterogeneous-lineage regime, and the one
    place sharded division pools can genuinely desynchronize (a fast
    lineage's daughters all recycle rows in the parent's shard; see
    tests/test_experiment.py::TestHeterogeneousDivergence).
    """

    name = "growth"
    defaults = {"rate": 0.0005, "per_agent_rates": False}
    # 1/s  (~23 min doubling, E. coli-ish)

    def ports_schema(self):
        schema = {
            "global": {
                "volume": {
                    "_default": 1.0,
                    "_updater": "accumulate",
                    "_divider": "split",
                },
            },
        }
        if self.config["per_agent_rates"]:
            schema["global"]["growth_rate"] = {
                "_default": float(self.config["rate"]),
                "_updater": "set",
                "_divider": "copy",
            }
        return schema

    def next_update(self, timestep, states):
        g = states["global"]
        rate = (
            g["growth_rate"]
            if self.config["per_agent_rates"]
            else self.config["rate"]
        )
        return {
            "global": {"volume": g["volume"] * (jnp.exp(rate * timestep) - 1.0)}
        }


@register
class DivideTrigger(Deriver):
    """Sets ``divide = volume >= threshold`` (the colony watches this)."""

    name = "divide_trigger"
    defaults = {"threshold": 2.0}

    def ports_schema(self):
        return {
            "global": {
                "volume": {"_default": 1.0, "_divider": "split"},
                "divide": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
        }

    def next_update(self, timestep, states):
        v = states["global"]["volume"]
        return {
            "global": {
                "divide": (v >= self.config["threshold"]).astype(jnp.float32)
            }
        }


@register
class DeathTrigger(Deriver):
    """Sets ``die = 1`` when a watched global variable crosses a
    threshold (the colony's ``death_trigger`` watches the flag).

    Default shape is starvation — die when ``volume`` shrinks below
    ``threshold`` — but ``variable``/``when`` configure any global
    scalar in either direction (e.g. a toxin accumulating past a limit
    with ``when="above"``). The watched variable's ``_default`` is
    configurable so shared-path declarations agree with whichever
    process owns it (core.engine requires identical declarations).
    """

    name = "death_trigger"
    defaults = {
        "variable": "volume",
        "threshold": 0.5,
        "when": "below",            # "below" | "above"
        "variable_default": 1.0,    # must match the owning process
        "variable_divider": "split",
    }

    def ports_schema(self):
        if self.config["when"] not in ("below", "above"):
            raise ValueError(
                f'death_trigger "when" must be "below" or "above", got '
                f'{self.config["when"]!r}'
            )
        return {
            "global": {
                self.config["variable"]: {
                    "_default": float(self.config["variable_default"]),
                    "_divider": str(self.config["variable_divider"]),
                },
                "die": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
        }

    def next_update(self, timestep, states):
        v = states["global"][self.config["variable"]]
        thr = self.config["threshold"]
        fire = v < thr if self.config["when"] == "below" else v > thr
        return {"global": {"die": fire.astype(jnp.float32)}}


@register
class Lysis(Deriver):
    """On death, release a cell's internal pool back to its lattice bin.

    Reads the die flag plus an internal nutrient pool; a dying cell
    loses its whole pool, and ``fraction`` of it enters the exchange
    port as secretion — the
    spatial layer then credits the cell's bin exactly as for any other
    secretion (unsharded, sharded, and multi-species alike), BEFORE the
    colony clears the alive bit, so the release lands in the field the
    same step the cell dies. What a dying cell hoarded returns to the
    commons: with ``fraction=1`` and matching units, death conserves
    total mass instead of deleting the pool with the frozen row.

    Order matters: insert AFTER the DeathTrigger process (derivers run
    in insertion order), so the flag read here is this step's verdict.
    ``fraction`` also converts units when the pool is not in field
    concentration units (e.g. MichaelisMentenTransport's ``yield_``).
    """

    name = "lysis"
    defaults = {
        "pool": "glucose_internal",
        "exchange": "glucose_exchange",
        "flag": "die",
        "fraction": 1.0,
    }

    def ports_schema(self):
        # shared-path declarations must MATCH the owners': the pool and
        # flag mirror MichaelisMentenTransport / DeathTrigger, the
        # exchange mirrors every transport's exchange declaration
        return {
            "internal": {
                self.config["pool"]: {
                    "_default": 0.0,
                    "_updater": "nonnegative_accumulate",
                    "_divider": "split",
                },
                self.config["flag"]: {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
            "exchange": {
                self.config["exchange"]: {
                    "_default": 0.0,
                    "_updater": "accumulate",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
        }

    def next_update(self, timestep, states):
        pool = states["internal"][self.config["pool"]]
        die = states["internal"][self.config["flag"]]
        # the dying cell loses its WHOLE pool; `fraction` scales what
        # reaches the field (unit conversion / recovery efficiency)
        dying = die > 0.0
        return {
            "internal": {
                self.config["pool"]: jnp.where(dying, -pool, 0.0)
            },
            "exchange": {
                self.config["exchange"]: jnp.where(
                    dying, pool * self.config["fraction"], 0.0
                )
            },
        }
