"""Growth and division-trigger processes.

The reference pairs a mass-accumulation growth process with a division
deriver that trips when the cell doubles (reconstructed:
``lens/processes/``, derivers in SURVEY.md §2 "Division/growth"). Here
growth is exponential in volume and the trigger is a plain schema variable
the colony layer watches (``Colony(division_trigger=...)``) — division
itself is row activation, not a handshake.
"""

from __future__ import annotations

import jax.numpy as jnp

from lens_tpu.core.process import Deriver, Process
from lens_tpu.processes import register


@register
class Growth(Process):
    """Exponential volume growth: V(t+dt) = V(t) * exp(rate * dt)."""

    name = "growth"
    defaults = {"rate": 0.0005}  # 1/s  (~23 min doubling, E. coli-ish)

    def ports_schema(self):
        return {
            "global": {
                "volume": {
                    "_default": 1.0,
                    "_updater": "accumulate",
                    "_divider": "split",
                },
            },
        }

    def next_update(self, timestep, states):
        v = states["global"]["volume"]
        return {"global": {"volume": v * (jnp.exp(self.config["rate"] * timestep) - 1.0)}}


@register
class DivideTrigger(Deriver):
    """Sets ``divide = volume >= threshold`` (the colony watches this)."""

    name = "divide_trigger"
    defaults = {"threshold": 2.0}

    def ports_schema(self):
        return {
            "global": {
                "volume": {"_default": 1.0, "_divider": "split"},
                "divide": {
                    "_default": 0.0,
                    "_updater": "set",
                    "_divider": "zero",
                    "_emit": False,
                },
            },
        }

    def next_update(self, timestep, states):
        v = states["global"]["volume"]
        return {
            "global": {
                "divide": (v >= self.config["threshold"]).astype(jnp.float32)
            }
        }
