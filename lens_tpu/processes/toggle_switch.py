"""Genetic toggle switch — the 4-species ODE expression Process.

Benchmark config 1 (BASELINE.json): "1k-agent colony, 4-species
toggle-switch ODE per agent, no lattice". A Gardner–Cantor–Collins (2000)
mutual-repression switch with explicit mRNA and protein for each arm::

    dmU/dt = a / (1 + (PV/k)^n) - dm * mU
    dPU/dt = kt * mU - dp * PU
    dmV/dt = a / (1 + (PU/k)^n) - dm * mV
    dPV/dt = kt * mV - dp * PV

This is the colony-scale vmap workhorse: no environment coupling, so it
isolates agent-axis stacking/scaling (SURVEY.md §7 step 4). Fills the
reference's gene-expression process slot (reconstructed:
``lens/processes/`` expression modules, SURVEY.md §2) with TPU-friendly
pure-jnp kinetics.
"""

from __future__ import annotations

import jax.numpy as jnp

from lens_tpu.core.process import Process
from lens_tpu.ops.integrate import odeint_window
from lens_tpu.processes import register


@register
class ToggleSwitch(Process):
    name = "toggle_switch"

    defaults = {
        "alpha": 2.0,     # max transcription rate
        "k": 1.0,         # repression threshold
        "n_hill": 2.0,    # Hill coefficient
        "d_m": 1.0,       # mRNA degradation 1/s
        "k_t": 1.0,       # translation rate 1/s
        "d_p": 0.5,       # protein degradation 1/s
        "substeps": 10,
        "method": "rk4",
    }

    def ports_schema(self):
        leaf = lambda default: {
            "_default": default,
            "_updater": "nonnegative_accumulate",
            "_divider": "split",
        }
        return {
            "internal": {
                "mrna_u": leaf(0.5),
                "protein_u": leaf(2.0),
                "mrna_v": leaf(0.1),
                "protein_v": leaf(0.1),
            },
        }

    def _rhs(self, t, y, args):
        m_u, p_u, m_v, p_v = y
        c = self.config
        hill = lambda p: c["alpha"] / (1.0 + (p / c["k"]) ** c["n_hill"])
        return (
            hill(p_v) - c["d_m"] * m_u,
            c["k_t"] * m_u - c["d_p"] * p_u,
            hill(p_u) - c["d_m"] * m_v,
            c["k_t"] * m_v - c["d_p"] * p_v,
        )

    def next_update(self, timestep, states):
        s = states["internal"]
        y0 = (s["mrna_u"], s["protein_u"], s["mrna_v"], s["protein_v"])
        n = max(int(self.config["substeps"]), 1)
        y = odeint_window(
            self._rhs, y0, 0.0, jnp.float32(timestep) / n, n,
            method=self.config["method"],
        )
        names = ("mrna_u", "protein_u", "mrna_v", "protein_v")
        return {"internal": {k: yf - y0_ for k, yf, y0_ in zip(names, y, y0)}}
