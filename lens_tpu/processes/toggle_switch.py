"""Genetic toggle switch — the 4-species ODE expression Process.

Benchmark config 1 (BASELINE.json): "1k-agent colony, 4-species
toggle-switch ODE per agent, no lattice". A Gardner–Cantor–Collins (2000)
mutual-repression switch with explicit mRNA and protein for each arm::

    dmU/dt = a / (1 + (PV/k)^n) - dm * mU
    dPU/dt = kt * mU - dp * PU
    dmV/dt = a / (1 + (PU/k)^n) - dm * mV
    dPV/dt = kt * mV - dp * PV

This is the colony-scale vmap workhorse: no environment coupling, so it
isolates agent-axis stacking/scaling (SURVEY.md §7 step 4). Fills the
reference's gene-expression process slot (reconstructed:
``lens/processes/`` expression modules, SURVEY.md §2) with TPU-friendly
pure-jnp kinetics.

``method="tau_leap"`` runs the SAME network stochastically: the four
ODE fluxes become eight discrete reaction channels (two Hill-gated
transcriptions, two translations, four decays) tau-leaped through
``ops.gillespie`` with the hybrid Poisson sampler (``sampler`` knob, see
``ops.sampling``) — the low-copy-number switch whose spontaneous state
flips the deterministic form cannot show. Gardner's original analysis
is bistable-ODE; the stochastic variant is the standard extension.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from lens_tpu.core.process import Process
from lens_tpu.ops.gillespie import tau_leap_window
from lens_tpu.ops.integrate import odeint_window
from lens_tpu.ops.sampling import check_sampler, check_threshold
from lens_tpu.processes import register

#: tau-leap stoichiometry [8, 4]; species order (m_u, p_u, m_v, p_v)
_TOGGLE_STOICH = jnp.asarray(
    np.kron(
        np.eye(2, dtype=np.float32),          # the U arm, then the V arm
        np.asarray(
            [
                [1.0, 0.0],    # transcription (Hill-gated by the other arm)
                [0.0, 1.0],    # translation
                [-1.0, 0.0],   # mRNA decay
                [0.0, -1.0],   # protein decay
            ],
            np.float32,
        ),
    )
)


@register
class ToggleSwitch(Process):
    name = "toggle_switch"

    defaults = {
        "alpha": 2.0,     # max transcription rate
        "k": 1.0,         # repression threshold
        "n_hill": 2.0,    # Hill coefficient
        "d_m": 1.0,       # mRNA degradation 1/s
        "k_t": 1.0,       # translation rate 1/s
        "d_p": 0.5,       # protein degradation 1/s
        "substeps": 10,
        "method": "rk4",  # integrate.odeint_window method, or "tau_leap"
        # Poisson sampler for method="tau_leap" only (ops.sampling)
        "sampler": "hybrid",
        "sampler_threshold": 10.0,
    }

    def __init__(self, config=None):
        super().__init__(config)
        check_sampler(self.config["sampler"])
        check_threshold(self.config["sampler_threshold"])
        if self.config["method"] == "tau_leap":
            # instance attr shadows the class flag: the engine supplies
            # a per-agent key only to stochastic processes
            self.stochastic = True

    def ports_schema(self):
        leaf = lambda default: {
            "_default": default,
            "_updater": "nonnegative_accumulate",
            "_divider": "split",
        }
        return {
            "internal": {
                "mrna_u": leaf(0.5),
                "protein_u": leaf(2.0),
                "mrna_v": leaf(0.1),
                "protein_v": leaf(0.1),
            },
        }

    def _rhs(self, t, y, args):
        m_u, p_u, m_v, p_v = y
        c = self.config
        hill = lambda p: c["alpha"] / (1.0 + (p / c["k"]) ** c["n_hill"])
        return (
            hill(p_v) - c["d_m"] * m_u,
            c["k_t"] * m_u - c["d_p"] * p_u,
            hill(p_u) - c["d_m"] * m_v,
            c["k_t"] * m_v - c["d_p"] * p_v,
        )

    def next_update(self, timestep, states, key=None):
        s = states["internal"]
        y0 = (s["mrna_u"], s["protein_u"], s["mrna_v"], s["protein_v"])
        n = max(int(self.config["substeps"]), 1)
        names = ("mrna_u", "protein_u", "mrna_v", "protein_v")
        if self.config["method"] == "tau_leap":
            c = self.config
            # The schema defaults are ODE-oriented FRACTIONAL counts
            # (mrna_u=0.5, ...); discrete kinetics on a fractional pool
            # leaves a permanent phantom residue (decay caps at
            # floor(pool), so 0.5 molecules can never decay yet still
            # contribute propensity). Round at entry: the returned delta
            # is (new - y0), so the accumulated state lands exactly on
            # the integral `new` after one step and stays integral.
            y0r = tuple(jnp.round(y) for y in y0)

            def propensities(x):
                m_u, p_u, m_v, p_v = x[0], x[1], x[2], x[3]
                hill = lambda p: c["alpha"] / (
                    1.0 + (jnp.maximum(p, 0.0) / c["k"]) ** c["n_hill"]
                )
                return jnp.stack(
                    [
                        hill(p_v), c["k_t"] * m_u,
                        c["d_m"] * m_u, c["d_p"] * p_u,
                        hill(p_u), c["k_t"] * m_v,
                        c["d_m"] * m_v, c["d_p"] * p_v,
                    ]
                )

            new = tau_leap_window(
                key, jnp.stack(y0r), _TOGGLE_STOICH, propensities,
                timestep, n,
                sampler=c["sampler"],
                threshold=float(c["sampler_threshold"]),
            )
            return {
                "internal": {
                    k: new[i] - y0[i] for i, k in enumerate(names)
                }
            }
        y = odeint_window(
            self._rhs, y0, 0.0, jnp.float32(timestep) / n, n,
            method=self.config["method"],
        )
        return {"internal": {k: yf - y0_ for k, yf, y0_ in zip(names, y, y0)}}
