"""The cluster router: locality-aware placement, work-stealing, and
whole-host failover over per-host serve workers.

``ClusterServer`` presents the ``SimServer`` client surface (submit /
status / result / cancel / resubmit / metrics / tick / close) while
fanning the work across one worker PER HOST (docs/serving.md, "Cluster
serving"):

- **placement** scores live hosts by queue depth and free lanes, with
  one override: a request declaring a shared prefix routes to the host
  whose snapshot tier already owns that prefix (sticky locality map) —
  UNLESS that host is backed up past ``steal_threshold``, in which
  case the request falls back to the least-loaded host and re-resolves
  there (recompute, or a shared-tier disk hit).
- **work-stealing** runs every router tick: when one host's FIFO backs
  up past ``steal_threshold`` while another sits idle with free lanes,
  the router withdraws queued requests from the rich host's tail
  (``SimServer.withdraw`` — WAL'd as MIGRATED locally) and resubmits
  them to the idle host under their original ids, so a skewed tenant
  cannot strand cluster capacity.
- **whole-host failover** generalizes device quarantine one level up:
  heartbeat loss (the health connection stops answering), a worker
  process exit, a scheduler-thread death, or a ``FaultPlan``
  ``host_down`` (which SIGKILLs the spawned worker — the drill is a
  real kill) drains the host from routing; its per-host WAL is read
  back, every WAL-known unfinished request re-queues onto survivors
  under its original id (``SimServer.adopt_displaced`` — the
  merge-on-recover semantics of device failover, now per host), and
  spill-backed snapshots re-adopt from the shared tier directory.

Two host transports share one op dispatch (``WorkerCore``):
``local=True`` runs simulated hosts in-process (the router ticks each
core — fast, no process spawns; the unit-test tier), ``local=False``
spawns one real worker process per host over localhost TCP (the drill
tier and the CLI/front-door deployment shape on one box; on real
fleets the same worker joins from each host via
``python -m lens_tpu cluster-worker``).

Thread model: the router is NOT internally locked — its callers
serialize (the front door's admission lock, or a single-threaded
driver), exactly like ``SimServer``.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from dataclasses import replace as dc_replace
from typing import Any, Dict, List, Mapping, Optional

from lens_tpu.cluster.protocol import (
    raise_error,
    recv_msg,
    rpc,
    send_msg,
)
from lens_tpu.cluster.worker import ID_SPAN, WorkerCore, _offset_ids
from lens_tpu.obs.trace import NullTracer, Tracer
from lens_tpu.serve.batcher import (
    CANCELLED,
    DONE,
    FAILED,
    MIGRATED,
    QUEUED,
    QueueFull,
    RUNNING,
    ScenarioRequest,
    SimulationDiverged,
    TIMEOUT,
)
from lens_tpu.serve.faults import FaultPlan
from lens_tpu.serve.metrics import ServerMetrics
from lens_tpu.serve.results import (
    ResultCache,
    log_config,
    request_fingerprint,
)
from lens_tpu.serve.wal import (
    buckets_fingerprint,
    classify_events,
    read_events,
    unfinished,
)

_TERMINAL = (DONE, FAILED, TIMEOUT, CANCELLED)

#: Cluster layout inside ``cluster_dir`` (one shared filesystem in the
#: simulated-hosts mode; a real fleet points these at shared storage).
OUT_DIR = "out"          # every host's per-request .lens logs
TIER_DIR = "tiers"       # shared snapshot tier + hold spills
HOST_DIR = "host{:02d}"  # per-host WAL dir, worker config/log/meta


class HostDown(ConnectionError):
    """A control call could not complete because its host died (the
    router declares the host down and the caller retries elsewhere)."""


@dataclass
class ClusterTicket:
    """The router's mirror of one request's state (refreshed from the
    owning worker's published snapshot every router tick)."""

    request_id: str
    request: ScenarioRequest
    host: Optional[int]          # owning host; None while in limbo
    status: str = QUEUED
    error: Optional[str] = None
    steps_done: int = 0
    horizon_steps: int = 0
    result_path: Optional[str] = None
    streamed_at: Optional[float] = None
    diverged: bool = False
    parent: Optional[str] = None
    internal: bool = False       # router tickets are always client work
    submitted_at: float = field(default_factory=time.perf_counter)
    finished_at: Optional[float] = None
    #: worker-reported timing row (worker-clock relative seconds)
    timing: Optional[Dict[str, Any]] = None
    #: stage marks the router never observes (a worker-side concern) —
    #: present so ``request_timing_row`` renders a router ticket too
    #: (the front door's fallback when the owning host is gone)
    shard: Optional[int] = None
    admitted_at: Optional[float] = None
    first_window_at: Optional[float] = None
    #: stream epoch: worker-level device requeues + router-level host
    #: failovers; a bump tells an SSE reader its sink restarted
    requeues: int = 0
    _fail_epochs: int = 0


class _Host:
    """One host's handle: identity, health mirror, WAL location."""

    def __init__(self, host_id: int, host_dir: str):
        self.host_id = int(host_id)
        self.host_dir = host_dir
        self.wal_dir = os.path.join(host_dir, "wal")
        self.alive = True
        self.misses = 0
        self.polled_at = 0.0
        self.health: Dict[str, Any] = {
            "queue_depth": 0, "lanes_busy": 0, "lanes_total": 0,
            "free_lanes": 0, "busy": False, "retry_after": 1.0,
            "counters": {}, "tickets": [], "alive": True,
            "version": 0, "quarantined_devices": 0,
        }

    # subclass surface -------------------------------------------------------

    def call(self, op: str, timeout: Optional[float] = None,
             **params: Any) -> Dict[str, Any]:
        raise NotImplementedError

    def poll(self) -> Dict[str, Any]:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        raise NotImplementedError


class LocalHost(_Host):
    """An in-process simulated host: the same ``WorkerCore`` dispatch,
    driven by the router's own tick (no subprocess, no sockets — the
    fast tier for routing/stealing/failover logic). Ops JSON-roundtrip
    so anything that would not survive the wire fails here too."""

    def __init__(self, host_id: int, host_dir: str, core: WorkerCore):
        super().__init__(host_id, host_dir)
        self.core = core

    def _roundtrip(self, obj: Any) -> Any:
        return json.loads(json.dumps(obj, default=str))

    def call(self, op: str, timeout: Optional[float] = None,
             **params: Any) -> Dict[str, Any]:
        if not self.alive:
            raise HostDown(f"host {self.host_id} is down")
        msg = self._roundtrip({"op": op, **params})
        reply = self._roundtrip(self.core.handle_control(msg))
        if not reply.get("ok"):
            raise_error(reply)
        return reply

    def tick(self) -> bool:
        if not self.alive:
            return False
        return self.core.tick_once()

    def poll(self) -> Dict[str, Any]:
        if not self.alive:
            raise HostDown(f"host {self.host_id} is down")
        reply = self.core.handle_health({"op": "poll"})
        if not reply.get("ok"):
            raise HostDown(f"host {self.host_id}: {reply.get('error')}")
        return reply

    def kill(self) -> None:
        # a crashed host stops doing work but is NOT closed cleanly —
        # its WAL (flushed at every append) is what failover reads
        self.alive = False

    def shutdown(self) -> None:
        if self.alive:
            self.alive = False
            self.core.close()


class RemoteHost(_Host):
    """A spawned worker process reached over localhost TCP: a control
    connection (lock-bound ops) and a health connection (lock-free
    ping/poll — answered even while the worker compiles)."""

    def __init__(
        self,
        host_id: int,
        host_dir: str,
        proc: subprocess.Popen,
        rpc_timeout_s: float,
        heartbeat_s: float,
    ):
        super().__init__(host_id, host_dir)
        self.proc = proc
        self.control: Optional[socket.socket] = None
        self.health_sock: Optional[socket.socket] = None
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.heartbeat_s = float(heartbeat_s)
        self.info: Dict[str, Any] = {}
        self._desynced = False

    def call(self, op: str, timeout: Optional[float] = None,
             **params: Any) -> Dict[str, Any]:
        if not self.alive or self.control is None:
            raise HostDown(f"host {self.host_id} is down")
        try:
            return rpc(
                self.control, op,
                timeout=timeout or self.rpc_timeout_s, **params,
            )
        except (ConnectionError, socket.timeout, OSError) as e:
            raise HostDown(
                f"host {self.host_id} control connection failed "
                f"during {op!r}: {e}"
            ) from e

    def poll(self) -> Dict[str, Any]:
        if not self.alive or self.health_sock is None:
            raise HostDown(f"host {self.host_id} is down")
        if self._desynced:
            self._resync()
        try:
            return rpc(
                self.health_sock, "poll",
                timeout=self.heartbeat_s,
                since=self.health.get("version"),
            )
        except socket.timeout:
            # ONE missed heartbeat is counted, not fatal (the router
            # tolerates heartbeat_misses of them). Must precede the
            # OSError arm: socket.timeout IS an OSError subclass.
            self._desynced = True
            raise
        except (ConnectionError, OSError) as e:
            raise HostDown(
                f"host {self.host_id} health connection failed: {e}"
            ) from e

    def _resync(self) -> None:
        """A timed-out poll abandoned its reply: the late frame (whole
        — or partial, since the timeout may have consumed some of its
        bytes) is still in the stream, and reading the next reply from
        here would be one snapshot stale forever, or land mid-frame
        and unpack payload bytes as a length prefix (which reads as a
        corrupt connection and would SIGKILL a healthy worker). Drain
        until the stream goes quiet; snapshots are idempotent, so the
        discarded replies cost nothing."""
        s = self.health_sock
        closed = False
        try:
            s.settimeout(0.05)
            while True:
                if not s.recv(65536):
                    closed = True
                    break
        except socket.timeout:
            self._desynced = False  # quiet: frame boundary restored
        except (ConnectionError, OSError) as e:
            raise HostDown(
                f"host {self.host_id} health connection failed "
                f"during resync: {e}"
            ) from e
        if closed:
            raise HostDown(
                f"host {self.host_id} health connection closed "
                f"during resync"
            )

    def kill(self) -> None:
        self.alive = False
        for s in (self.control, self.health_sock):
            try:
                if s is not None:
                    s.close()
            except OSError:
                pass
        self.control = self.health_sock = None
        if self.proc.poll() is None:
            self.proc.kill()
        try:
            self.proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass

    def shutdown(self) -> None:
        if self.alive and self.control is not None:
            try:
                self.call("shutdown", timeout=120.0)
            except Exception:
                pass
            try:
                # let the worker finish its clean close (drain the
                # streamer, write server_meta) before the backstop kill
                self.proc.wait(timeout=180)
            except subprocess.TimeoutExpired:
                pass
        self.kill()


class _ClusterQueue:
    """Duck-typed ``RequestQueue`` view for the front door's pump gate:
    cluster-wide queued count against cluster-wide depth."""

    def __init__(self, owner: "ClusterServer"):
        self._owner = owner
        self.max_depth = 0

    def __len__(self) -> int:
        o = self._owner
        return sum(
            h.health["queue_depth"] for h in o.hosts.values() if h.alive
        ) + len(o._limbo) + len(o._displaced)


class _BucketView:
    """Duck-typed bucket for the front door's drain check
    (``b.busy()``) and composite discovery."""

    def __init__(self, owner: "ClusterServer", name: str):
        self._owner = owner
        self.name = name

    def busy(self) -> int:
        return sum(
            h.health["lanes_busy"]
            for h in self._owner.hosts.values()
            if h.alive
        )


class ClusterServer:
    """Multi-host serving: one worker per host behind this router.

    Parameters
    ----------
    buckets:
        The same ``{name: bucket_config}`` mapping as ``SimServer`` —
        every host serves every bucket (homogeneous fleet; the
        fingerprint is verified at join).
    hosts:
        Host count. Simulated-hosts mode on one box: the router spawns
        that many workers (``local=False``, real processes over
        localhost TCP) or runs them in-process (``local=True``).
    cluster_dir:
        Root for everything host-crossing: ``out/`` (shared result
        logs), ``tiers/`` (shared snapshot tier + hold spills — what
        failover re-adopts from), ``host<k>/`` (per-host WAL dir,
        worker config/log/meta).
    queue_depth:
        PER-HOST bounded queue depth (cluster capacity is the sum).
    worker:
        Extra ``SimServer`` kwargs forwarded to every worker
        (``pipeline``, ``check_finite``, ``mesh``, per-worker
        ``faults`` spec, ...).
    heartbeat_s / heartbeat_misses:
        Health poll timeout and how many consecutive misses declare a
        host down. Health polls are answered lock-free by the worker,
        so a long compile never reads as death; a SIGKILLed worker
        fails the connection outright and is declared down
        immediately.
    steal_threshold / steal_batch:
        A host whose queue depth reaches the threshold while another
        host idles with free lanes loses up to ``steal_batch`` queued
        requests per router tick to the idle host. The threshold also
        bounds locality routing: a prefix owner backed up past it
        loses its stickiness for new forks.
    faults:
        A ``FaultPlan`` for ROUTER-level chaos: ``host_down`` faults
        fire here (SIGKILLing spawned workers). Worker-level faults
        (nan/io_error/kill seams) ride ``worker={"faults": spec}``.
    trace_dir:
        Arm tracing: the router's spans land in
        ``<trace_dir>/cluster.trace``; each worker traces to
        ``<trace_dir>/host<k>/serve.trace`` with a ``host`` label on
        every event.
    worker_env:
        Extra environment for spawned workers (e.g. ``XLA_FLAGS`` for
        simulated devices under a per-host mesh).
    """

    def __init__(
        self,
        buckets: Mapping[str, Mapping[str, Any]],
        hosts: int = 2,
        cluster_dir: Optional[str] = None,
        queue_depth: int = 64,
        local: bool = False,
        worker: Optional[Mapping[str, Any]] = None,
        heartbeat_s: float = 5.0,
        heartbeat_misses: int = 3,
        poll_s: float = 0.01,
        rpc_timeout_s: float = 300.0,
        steal_threshold: int = 2,
        steal_batch: int = 2,
        faults: Optional[FaultPlan] = None,
        trace_dir: Optional[str] = None,
        worker_env: Optional[Mapping[str, str]] = None,
        spawn_timeout_s: float = 300.0,
        result_cache_mb: Optional[float] = None,
        dedup: str = "off",
    ):
        if int(hosts) < 1:
            raise ValueError(f"hosts={hosts} must be >= 1")
        if not cluster_dir:
            raise ValueError(
                "ClusterServer needs a cluster_dir (shared logs, "
                "tiers, and per-host WALs live under it)"
            )
        if dedup not in ("on", "off"):
            raise ValueError(
                f"dedup={dedup!r} must be 'on' or 'off'"
            )
        if result_cache_mb is not None \
                and float(result_cache_mb) <= 0:
            raise ValueError(
                f"result_cache_mb={result_cache_mb} must be > 0"
            )
        self.n_hosts = int(hosts)
        self.cluster_dir = os.path.abspath(cluster_dir)
        self.out_dir = os.path.join(self.cluster_dir, OUT_DIR)
        self.tier_dir = os.path.join(self.cluster_dir, TIER_DIR)
        os.makedirs(self.out_dir, exist_ok=True)
        os.makedirs(self.tier_dir, exist_ok=True)
        self.sink = "log"  # the front door's duck check
        self.local = bool(local)
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_misses = int(heartbeat_misses)
        self.poll_s = float(poll_s)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.steal_threshold = int(steal_threshold)
        self.steal_batch = int(steal_batch)
        self.faults = faults if faults is not None else FaultPlan(None)
        self.trace_dir = trace_dir
        if trace_dir:
            os.makedirs(trace_dir, exist_ok=True)
            self.trace: Any = Tracer(
                os.path.join(trace_dir, "cluster.trace"),
                extra={"role": "router"},
            )
        else:
            self.trace = NullTracer()
        self.faults.trace = self.trace
        self._metrics = ServerMetrics()
        self.queue = _ClusterQueue(self)
        self.queue.max_depth = int(queue_depth) * self.n_hosts
        self.buckets: Dict[str, _BucketView] = {
            name: _BucketView(self, name) for name in buckets
        }
        self.tickets: Dict[str, ClusterTicket] = {}
        self._rids = itertools.count()
        self._limbo: List[Dict[str, Any]] = []      # stolen, unplaced
        self._displaced: List[str] = []             # failover retries
        self._dead_events: Dict[int, List[Dict[str, Any]]] = {}
        self._rid_dead_host: Dict[str, int] = {}
        self._prefix_owner: Dict[str, int] = {}
        self._ticks = 0
        self._closed = False
        # -- request-stream CDN (round 18) --
        # The router answers result-cache hits BEFORE host placement:
        # its cache instance reads the SAME shared results dir every
        # worker files into (tiers/results — the workers get tier_dir
        # and derive the same path), so a repeat of any host's work is
        # served here with zero routing, zero queueing, zero device
        # windows. Budget/GC stay with the workers (they own the
        # writes and see every entry); the router only reads, and
        # `refresh` adopts entries published after its scan.
        self.result_cache_mb = result_cache_mb
        self.dedup = dedup
        self._result_cache: Optional[ResultCache] = None
        if result_cache_mb is not None:
            from lens_tpu.serve.server import BUCKET_DEFAULTS
            from lens_tpu.utils.dicts import deep_merge

            self._result_cache = ResultCache(
                os.path.join(self.tier_dir, "results"),
                budget_bytes=None,
                fingerprint=buckets_fingerprint({
                    n: deep_merge(BUCKET_DEFAULTS, c or {})
                    for n, c in buckets.items()
                }),
            )
        self.hosts: Dict[int, _Host] = {}
        worker = dict(worker or {})
        if result_cache_mb is not None:
            worker.setdefault("result_cache_mb", result_cache_mb)
        if dedup == "on":
            worker.setdefault("dedup", dedup)
        self._spawn(buckets, worker, queue_depth, worker_env,
                    float(spawn_timeout_s))
        self._recovered = self._mirror_recovered()

    # -- bring-up ------------------------------------------------------------

    def _worker_kwargs(
        self, host_id: int, buckets, worker, queue_depth,
    ) -> Dict[str, Any]:
        host_dir = os.path.join(
            self.cluster_dir, HOST_DIR.format(host_id)
        )
        os.makedirs(host_dir, exist_ok=True)
        kwargs: Dict[str, Any] = {
            "queue_depth": int(queue_depth),
            "out_dir": self.out_dir,
            "sink": "log",
            "tier_dir": self.tier_dir,
            "recover_dir": os.path.join(host_dir, "wal"),
            **worker,
        }
        if self.trace_dir:
            kwargs.setdefault(
                "trace_dir",
                os.path.join(self.trace_dir, f"host{host_id:02d}"),
            )
        return kwargs

    def _spawn(self, buckets, worker, queue_depth, worker_env,
               spawn_timeout_s) -> None:
        if self.local:
            from lens_tpu.serve import SimServer

            for k in range(self.n_hosts):
                host_dir = os.path.join(
                    self.cluster_dir, HOST_DIR.format(k)
                )
                kwargs = self._worker_kwargs(
                    k, buckets, worker, queue_depth
                )
                fault_spec = kwargs.pop("faults", None)
                if fault_spec is not None:
                    # same conversion the subprocess entry does
                    # (worker._build_server): a worker faults spec
                    # injects in local mode too
                    kwargs["faults"] = FaultPlan.from_spec(fault_spec)
                srv = SimServer(buckets, **kwargs)
                srv.meta_dir = host_dir
                _offset_ids(srv, ID_SPAN * (k + 1))
                if srv.trace:
                    srv.trace.extra = {"host": k}
                self.hosts[k] = LocalHost(
                    k, host_dir, WorkerCore(srv, k)
                )
            return
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.bind(("127.0.0.1", 0))
        listener.listen(2 * self.n_hosts + 2)
        port = listener.getsockname()[1]
        procs: Dict[int, subprocess.Popen] = {}
        logs = []
        try:
            for k in range(self.n_hosts):
                host_dir = os.path.join(
                    self.cluster_dir, HOST_DIR.format(k)
                )
                kwargs = self._worker_kwargs(
                    k, buckets, worker, queue_depth
                )
                cfg = {
                    "host_id": k,
                    "n_hosts": self.n_hosts,
                    "join_host": "127.0.0.1",
                    "join_port": port,
                    "buckets": {
                        n: dict(c or {}) for n, c in buckets.items()
                    },
                    "server": kwargs,
                    "meta_dir": host_dir,
                }
                cfg_path = os.path.join(host_dir, "worker.json")
                with open(cfg_path, "w") as f:
                    json.dump(cfg, f, indent=1, default=str)
                log = open(os.path.join(host_dir, "worker.log"), "w")
                logs.append(log)
                env = dict(os.environ)
                if worker_env:
                    env.update(worker_env)
                procs[k] = subprocess.Popen(
                    [sys.executable, "-m", "lens_tpu",
                     "cluster-worker", "--config", cfg_path],
                    stdout=log, stderr=subprocess.STDOUT, env=env,
                )
            # accept control + health from every worker (jax import
            # dominates the wait; workers come up in parallel)
            deadline = time.monotonic() + spawn_timeout_s
            pending = {(k, role) for k in procs
                       for role in ("control", "health")}
            conns: Dict[tuple, socket.socket] = {}
            infos: Dict[int, Dict[str, Any]] = {}
            while pending:
                for k, p in procs.items():
                    if p.poll() is not None and any(
                        key[0] == k for key in pending
                    ):
                        raise RuntimeError(
                            f"cluster worker host {k} exited rc="
                            f"{p.returncode} during bring-up; see "
                            f"{os.path.join(self.cluster_dir, HOST_DIR.format(k), 'worker.log')}"
                        )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"cluster bring-up timed out with "
                        f"{sorted(pending)} still unjoined"
                    )
                listener.settimeout(min(remaining, 1.0))
                try:
                    conn, _ = listener.accept()
                except socket.timeout:
                    continue
                conn.settimeout(60)
                hello = recv_msg(conn)
                k = int(hello["host_id"])
                role = hello.get("role")
                if (k, role) not in pending:
                    conn.close()
                    raise RuntimeError(
                        f"unexpected cluster join host={k} "
                        f"role={role!r}"
                    )
                send_msg(conn, {"ok": True})
                pending.discard((k, role))
                conns[(k, role)] = conn
                if role == "control":
                    infos[k] = {
                        kk: v for kk, v in hello.items()
                        if kk not in ("op", "role")
                    }
            fps = {infos[k].get("fingerprint") for k in procs}
            if len(fps) > 1:
                raise RuntimeError(
                    f"cluster workers disagree on the bucket "
                    f"fingerprint: {sorted(fps)} — a heterogeneous "
                    f"fleet would serve different bits under one id "
                    f"space"
                )
            for k, p in procs.items():
                host_dir = os.path.join(
                    self.cluster_dir, HOST_DIR.format(k)
                )
                h = RemoteHost(
                    k, host_dir, p,
                    rpc_timeout_s=self.rpc_timeout_s,
                    heartbeat_s=self.heartbeat_s,
                )
                h.control = conns[(k, "control")]
                h.health_sock = conns[(k, "health")]
                h.info = infos.get(k, {})
                self.hosts[k] = h
        except BaseException:
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            raise
        finally:
            listener.close()
            for log in logs:
                log.close()

    _RID_RE = re.compile(r"req-(\d+)$")

    def _mirror_recovered(self) -> int:
        """A rerun over an existing ``cluster_dir``: every worker just
        replayed its per-host WAL at construction (``recover_dir`` is
        always armed), re-queueing unfinished work and finalizing
        WAL-attested results — but this router's mirror starts empty
        and its rid mint at zero. Read the same WALs back (shared
        filesystem): register a ``ClusterTicket`` per WAL-known client
        rid, so ``status``/``result`` and the serve CLI's resume trim
        see the previous invocation's work, and advance the mint past
        every known id — a fresh submit minting ``req-000000`` against
        a recovered ``req-000000`` would share its ticket slot AND its
        shared ``out/`` log file. Returns the re-queued (unfinished)
        count, which the ``recovered`` property reports before the
        first health poll lands."""
        requeued = 0
        floor = -1
        for h in self.hosts.values():
            try:
                events = read_events(h.wal_dir)
            except FileNotFoundError:
                continue  # first run: nothing to mirror
            if not events:
                continue
            order, recs, retired, streamed, holds, _released = (
                classify_events(events)
            )
            for rid in order:
                m = self._RID_RE.match(rid)
                if m is None or int(m.group(1)) >= ID_SPAN:
                    # not router-minted: a worker-internal ticket
                    # (id-mint offset space) — never mirrored
                    continue
                floor = max(floor, int(m.group(1)))
                fin = retired.get(rid)
                existing = self.tickets.get(rid)
                if existing is not None and (
                    existing.status != MIGRATED
                    or (fin or {}).get("status") == MIGRATED
                ):
                    # a MIGRATED retire means the rid moved on: the
                    # host holding the live copy wins the mirror slot
                    continue
                try:
                    request = self._wal_request(rid, recs)
                except (KeyError, ValueError, TypeError) as e:
                    self.trace.instant(
                        "cluster.mirror.skipped", rid=rid,
                        host=h.host_id, error=str(e),
                    )
                    continue
                t = ClusterTicket(
                    request_id=rid, request=request, host=h.host_id,
                    parent=recs[rid].get("parent"),
                )
                if fin is not None and not (
                    fin.get("status") == DONE and rid not in streamed
                ):
                    # same WAL-attested-finished rule the workers
                    # apply: a retired-DONE-but-unstreamed rid re-ran
                    t.status = str(fin.get("status"))
                    t.error = fin.get("error")
                    t.steps_done = int(fin.get("steps", 0))
                    t.diverged = (
                        "SimulationDiverged" in str(t.error or "")
                    )
                    if rid in streamed:
                        t.streamed_at = time.perf_counter()
                    t.finished_at = time.perf_counter()
                    path = os.path.join(self.out_dir, f"{rid}.lens")
                    if os.path.exists(path):
                        t.result_path = path
                else:
                    requeued += 1
                self.tickets[rid] = t
        if floor >= 0:
            self._rids = itertools.count(floor + 1)
        return requeued

    def _wal_request(
        self, rid: str, recs: Mapping[str, Mapping[str, Any]]
    ) -> ScenarioRequest:
        """The full-horizon request a WAL record denotes (mirror of
        ``SimServer._effective_request``): a continuation extends its
        parent chain's horizon."""
        rec = recs[rid]
        if "request" in rec:
            return ScenarioRequest.from_mapping(rec["request"])
        parent = self._wal_request(rec["parent"], recs)
        return dc_replace(
            parent,
            horizon=(
                float(parent.horizon) + float(rec["extra_horizon"])
            ),
        )

    # -- placement -----------------------------------------------------------

    def _live(self) -> List[_Host]:
        return [h for h in self.hosts.values() if h.alive]

    def _score(self, h: _Host) -> tuple:
        s = h.health
        return (
            s["queue_depth"],
            -s["free_lanes"],
            s["lanes_busy"],
            h.host_id,
        )

    def _route(self, request: ScenarioRequest) -> List[_Host]:
        """Candidate hosts, best first. Locality: a prefix fork
        prefers the host whose tier owns its snapshot unless that
        host is backed up past steal_threshold (then the fork falls
        back to the least-loaded host and re-resolves there)."""
        live = sorted(self._live(), key=self._score)
        if not live:
            raise ValueError(
                "every cluster host is down; the router has no "
                "schedulable capacity"
            )
        key = self._prefix_key(request)
        if key is not None:
            owner = self._prefix_owner.get(key)
            h = self.hosts.get(owner) if owner is not None else None
            if (
                h is not None and h.alive
                and h.health["queue_depth"] < self.steal_threshold
            ):
                return [h] + [x for x in live if x is not h]
        return live

    @staticmethod
    def _prefix_key(request: ScenarioRequest) -> Optional[str]:
        spec = request.prefix_spec()
        if spec is None:
            return None
        return json.dumps(spec, sort_keys=True, default=str)

    # -- client surface ------------------------------------------------------

    @property
    def recovered(self) -> int:
        """Requests the workers re-admitted from their own WALs at
        bring-up (a rerun over an existing cluster_dir resumes). The
        bring-up mirror count answers before the first health poll
        populates the workers' own counters; max() because both count
        the same replays."""
        return max(
            self._recovered,
            sum(
                h.health.get("counters", {}).get("recovered", 0)
                for h in self.hosts.values()
            ),
        )

    def reserve_id(self) -> str:
        return f"req-{next(self._rids):06d}"

    def reset_samples(self) -> None:
        """Bench hygiene parity with ``SimServer.reset_samples``: the
        router keeps no latency samples of its own (wall clocks live
        in the workers), so this only clears the door-side histogram
        state."""
        self._metrics.reset_samples()

    def retry_after_hint(self) -> float:
        live = self._live()
        if not live:
            return 5.0
        return max(
            min(h.health["retry_after"] for h in live), 0.05
        )

    def validate(
        self, request: ScenarioRequest | Mapping[str, Any]
    ) -> ScenarioRequest:
        """Shape-validate locally, schema-validate on a live worker
        (override paths and grids live where the models do)."""
        if isinstance(request, Mapping):
            request = ScenarioRequest.from_mapping(request)
        live = sorted(self._live(), key=self._score)
        if not live:
            raise ValueError(
                "every cluster host is down; cannot validate"
            )
        from lens_tpu.serve.server import _request_to_json

        for h in live:
            try:
                h.call("validate", request=_request_to_json(request))
                return request
            except HostDown:
                self._declare_down(h.host_id, "validate RPC failed")
        raise ValueError("every cluster host died during validation")

    def submit(
        self,
        request: ScenarioRequest | Mapping[str, Any],
        rid: Optional[str] = None,
        host: Optional[int] = None,
    ) -> str:
        """Route one request to a host and mirror its ticket here.
        ``host`` pins placement (tests/bench); default is the
        locality/load score. All hosts full raises ``QueueFull`` with
        the best (smallest) retry-after among them."""
        if isinstance(request, Mapping):
            request = ScenarioRequest.from_mapping(request)
        rid = rid if rid is not None else self.reserve_id()
        from lens_tpu.serve.server import _request_to_json

        payload = _request_to_json(request)
        if (
            self._result_cache is not None
            and not request.hold_state
            and self._serve_cached(request, payload, rid)
        ):
            return rid
        if host is not None:
            h = self.hosts.get(int(host))
            if h is None or not h.alive:
                raise ValueError(f"host {host} is not a live host")
            candidates: List[_Host] = [h]
        else:
            candidates = self._route(request)
        full: List[QueueFull] = []
        for h in candidates:
            try:
                h.call("submit", request=payload, rid=rid)
            except QueueFull as e:
                full.append(e)
                continue
            except HostDown:
                self._declare_down(h.host_id, "submit RPC failed")
                continue
            self._metrics.inc("submitted")
            self._metrics.tenant_inc(request.tenant, "admitted")
            t = ClusterTicket(
                request_id=rid, request=request, host=h.host_id,
            )
            self.tickets[rid] = t
            key = self._prefix_key(request)
            if key is not None:
                self._prefix_owner[key] = h.host_id
            h.health["queue_depth"] += 1  # optimistic, until next poll
            self.trace.instant(
                "cluster.routed", rid=rid, host=h.host_id,
            )
            return rid
        if full:
            self._metrics.inc("rejected")
            self._metrics.tenant_inc(request.tenant, "rejected")
            raise QueueFull(
                min(e.retry_after for e in full),
                max(getattr(e, "depth", 0) for e in full),
            )
        raise ValueError(
            "every cluster host is down; the router has no "
            "schedulable capacity"
        )

    def _serve_cached(
        self,
        request: ScenarioRequest,
        payload: Mapping[str, Any],
        rid: str,
    ) -> bool:
        """Answer one submit from the shared result cache AT THE
        ROUTER — no placement, no worker RPC, no queue slot anywhere.
        The cached log replays as the new rid's own ``<rid>.lens``
        under the shared out/ dir (header re-minted, every other frame
        verbatim), and the mirror ticket is born terminal with
        ``host=None`` — the same no-owner shape a failed-over terminal
        mirror has, so status/result/cancel already handle it. Any
        replay failure degrades to a miss and placement proceeds."""
        fp = request_fingerprint(payload)
        cache = self._result_cache
        if fp not in cache and not cache.refresh(fp):
            self._metrics.inc("result_misses")
            return False
        path = os.path.join(self.out_dir, f"{rid}.lens")
        if not cache.serve(fp, rid, log_config(request), path):
            self._metrics.inc("result_misses")
            return False
        now = time.perf_counter()
        t = ClusterTicket(
            request_id=rid, request=request, host=None, status=DONE,
        )
        t.result_path = path
        t.finished_at = now
        t.streamed_at = now
        self.tickets[rid] = t
        self._metrics.inc("submitted")
        self._metrics.inc("result_hits")
        self._metrics.tenant_inc(request.tenant, "admitted")
        self.trace.instant(
            "result.replay", rid=rid, tick=self._ticks,
        )
        return True

    def _ticket(self, request_id: str) -> ClusterTicket:
        t = self.tickets.get(request_id)
        if t is None:
            raise KeyError(f"unknown request id {request_id!r}")
        return t

    def status(self, request_id: str) -> Dict[str, Any]:
        t = self._ticket(request_id)
        h = self.hosts.get(t.host) if t.host is not None else None
        if h is not None and h.alive:
            try:
                out = h.call("status", rid=request_id)
                out.pop("ok", None)
                self._apply_row(h, out | {"rid": request_id})
                out["server"] = self._gauges()
                return out
            except HostDown:
                self._declare_down(h.host_id, "status RPC failed")
        return {
            "request_id": request_id,
            "status": t.status,
            "steps_done": t.steps_done,
            "horizon_steps": t.horizon_steps,
            "error": t.error,
            "result_path": t.result_path,
            "parent": t.parent,
            "host": t.host,
            "timing": t.timing,
            "server": self._gauges(),
        }

    def result(self, request_id: str) -> str:
        """The request's ``.lens`` log path (shared filesystem),
        after the owning worker attests the stream durable."""
        t = self._ticket(request_id)
        if t.diverged:
            raise SimulationDiverged(t.error)
        h = self.hosts.get(t.host) if t.host is not None else None
        if h is not None and h.alive:
            try:
                reply = h.call("result", rid=request_id)
            except HostDown:
                self._declare_down(h.host_id, "result RPC failed")
            else:
                t.result_path = reply["path"]
                return reply["path"]
        if t.result_path and t.status in _TERMINAL and t.streamed_at:
            return t.result_path
        cause = f": {t.error}" if t.error else ""
        raise ValueError(
            f"request {request_id} ({t.status}) has no durable result "
            f"and its host is gone{cause}"
        )

    def cancel(self, request_id: str) -> str:
        t = self._ticket(request_id)
        if t.status in _TERMINAL:
            return t.status
        if t.host is None:
            # in limbo between hosts: cancel at the router
            self._limbo = [
                e for e in self._limbo if e["rid"] != request_id
            ]
            self._displaced = [
                r for r in self._displaced if r != request_id
            ]
            t.status = CANCELLED
            t.finished_at = time.perf_counter()
            self._metrics.inc("cancelled")
            return t.status
        h = self.hosts.get(t.host)
        if h is None or not h.alive:
            return t.status
        try:
            reply = h.call("cancel", rid=request_id)
        except HostDown:
            self._declare_down(h.host_id, "cancel RPC failed")
            return t.status
        t.status = reply["status"]
        return t.status

    def resubmit(self, request_id: str, extra_horizon: float) -> str:
        """Extend a held DONE request — routed to the host holding its
        snapshot; if that host died, the parent re-adopts onto a
        survivor from the dead WAL + shared tier first."""
        t = self._ticket(request_id)
        h = self.hosts.get(t.host) if t.host is not None else None
        if h is None or not h.alive:
            h = self._adopt_finished(t)
        reply = h.call(
            "resubmit", rid=request_id,
            extra_horizon=float(extra_horizon),
        )
        new_rid = reply["rid"]
        self._metrics.inc("resubmitted")
        self.tickets[new_rid] = ClusterTicket(
            request_id=new_rid,
            request=dc_replace(
                t.request,
                horizon=float(t.request.horizon)
                + float(extra_horizon),
            ),
            host=h.host_id,
            parent=request_id,
        )
        return new_rid

    def release_state(self, request_id: str) -> None:
        t = self._ticket(request_id)
        h = self.hosts.get(t.host) if t.host is not None else None
        if h is None or not h.alive:
            return  # the hold died with its host's device memory
        h.call("release", rid=request_id)

    def prewarm(self, spec: Mapping[str, Any]) -> None:
        """Speculatively warm a prefix on the host that owns it (or
        the least-loaded host for a cold one), and make that host the
        prefix's locality owner so the forks this warming anticipates
        route to the warmed snapshot."""
        key = json.dumps({
            "composite": spec["composite"],
            "seed": int(spec.get("seed", 0)),
            "horizon": float(spec["horizon"]),
            "overrides": spec.get("overrides") or {},
            "n_agents": spec.get("n_agents"),
        }, sort_keys=True, default=str)
        live = sorted(self._live(), key=self._score)
        if not live:
            return
        owner = self._prefix_owner.get(key)
        h = self.hosts.get(owner) if owner is not None else None
        if h is None or not h.alive:
            h = live[0]
        h.call("prewarm", spec=dict(spec))
        self._prefix_owner[key] = h.host_id

    # -- scheduling ----------------------------------------------------------

    def tick(self) -> bool:
        """One router iteration: injected host faults, local-host
        ticks, health polls (the heartbeat), failover for newly dead
        hosts, limbo/displaced drains, and one stealing pass."""
        self._ticks += 1
        self._metrics.inc("ticks")
        for h in list(self.hosts.values()):
            if h.alive and self.faults.host_down(h.host_id):
                # the injected whole-host failure: a REAL SIGKILL for
                # spawned workers (LocalHost marks itself dead)
                h.kill()
                self._declare_down(
                    h.host_id, "FaultPlan host_down"
                )
        busy = False
        for h in self.hosts.values():
            if isinstance(h, LocalHost) and h.alive:
                busy = h.tick() or busy
        now = time.perf_counter()
        for h in list(self.hosts.values()):
            if not h.alive:
                continue
            if (
                isinstance(h, RemoteHost)
                and now - h.polled_at < self.poll_s
            ):
                # health mirrors are advisory: polling a remote worker
                # faster than poll_s burns both sides' CPU shipping
                # identical snapshots (LocalHosts are polled in-line —
                # free — every tick)
                busy = busy or h.health.get("busy", False)
                continue
            if (
                isinstance(h, RemoteHost)
                and h.proc.poll() is not None
            ):
                self._declare_down(
                    h.host_id,
                    f"worker process exited rc={h.proc.returncode}",
                )
                continue
            try:
                snap = h.poll()
            except socket.timeout:
                h.misses += 1
                if h.misses >= self.heartbeat_misses:
                    self._declare_down(
                        h.host_id,
                        f"heartbeat lost ({h.misses} consecutive "
                        f"misses at {self.heartbeat_s}s)",
                    )
                continue
            except HostDown as e:
                self._declare_down(h.host_id, str(e))
                continue
            h.misses = 0
            h.polled_at = now
            if not snap.get("unchanged"):
                h.health = {**h.health, **{
                    k: v for k, v in snap.items() if k != "ok"
                }}
                for row in h.health.get("tickets", ()):
                    self._apply_row(h, row)
            if not snap.get("unchanged", False) and not h.health.get(
                "alive", True
            ):
                self._declare_down(
                    h.host_id,
                    f"worker scheduler died: {h.health.get('error')}",
                )
                continue
            busy = busy or h.health.get("busy", False)
        self._drain_displaced()
        self._drain_limbo()
        self._steal_pass()
        return bool(busy or self._limbo or self._displaced)

    def run_until_idle(self, max_ticks: Optional[int] = None) -> int:
        """Drive ``tick`` until every host reports idle (two
        consecutive quiet passes — health mirrors are one poll stale
        by construction)."""
        ticks = 0
        quiet = 0
        while True:
            busy = self.tick()
            ticks += 1
            if busy:
                quiet = 0
            else:
                quiet += 1
                if quiet >= 2:
                    return ticks
            if max_ticks is not None and ticks >= max_ticks:
                raise RuntimeError(
                    f"cluster not idle after {ticks} router ticks "
                    f"(queue={len(self.queue)}, "
                    f"limbo={len(self._limbo)})"
                )
            if not self.local:
                # workers tick themselves; the router only needs to
                # wake for routing decisions. When every host is busy
                # and nothing waits on the router, back off — on an
                # oversubscribed box each router wakeup preempts a
                # worker's compute
                idle_router = (
                    not self._limbo and not self._displaced
                    and all(
                        h.health.get("busy") or h.health["queue_depth"]
                        for h in self._live()
                    )
                )
                time.sleep(0.01 if idle_router else 0.002)

    def _apply_row(self, h: _Host, row: Mapping[str, Any]) -> None:
        rid = row.get("rid") or row.get("request_id")
        t = self.tickets.get(rid)
        if t is None or t.host != h.host_id:
            return  # stale owner (stolen/displaced) or internal
        t.status = row["status"]
        t.error = row.get("error")
        t.steps_done = int(row.get("steps_done", t.steps_done))
        t.horizon_steps = int(
            row.get("horizon_steps", t.horizon_steps)
        )
        t.diverged = bool(row.get("diverged", False))
        if row.get("result_path"):
            t.result_path = row["result_path"]
        if row.get("timing"):
            t.timing = dict(row["timing"])
        t.requeues = int(row.get("requeues", 0)) + t._fail_epochs
        if row.get("streamed"):
            if t.streamed_at is None:
                t.streamed_at = time.perf_counter()
        else:
            t.streamed_at = None
        if t.status in _TERMINAL and t.finished_at is None:
            t.finished_at = time.perf_counter()

    # -- work-stealing -------------------------------------------------------

    def _steal_pass(self) -> None:
        live = self._live()
        if len(live) < 2:
            return
        donor = max(live, key=lambda h: h.health["queue_depth"])
        if donor.health["queue_depth"] < self.steal_threshold:
            return
        takers = [
            h for h in live
            if h is not donor
            and h.health["queue_depth"] == 0
            and h.health["free_lanes"] > 0
        ]
        if not takers:
            return
        taker = max(takers, key=lambda h: h.health["free_lanes"])
        want = min(
            self.steal_batch,
            taker.health["free_lanes"],
            donor.health["queue_depth"] - 1,
        )
        if want < 1:
            return
        try:
            reply = donor.call("withdraw", count=want)
        except HostDown:
            self._declare_down(donor.host_id, "withdraw RPC failed")
            return
        stolen = reply.get("requests", [])
        if not stolen:
            return
        donor.health["queue_depth"] = max(
            donor.health["queue_depth"] - len(stolen), 0
        )
        for item in stolen:
            rid = item["rid"]
            self._metrics.inc("stolen")
            t = self.tickets.get(rid)
            if t is not None:
                t.host = None
            self.trace.instant(
                "cluster.stolen", rid=rid,
                src=donor.host_id, dst=taker.host_id,
            )
            self._place(rid, item["request"], prefer=taker)

    def _place(
        self, rid: str, request_json: Mapping[str, Any],
        prefer: Optional[_Host] = None,
    ) -> None:
        """(Re)submit a router-held request (stolen or displaced-
        retry) under its original id; unplaceable work stays in
        limbo for the next tick."""
        t = self.tickets.get(rid)
        candidates = sorted(self._live(), key=self._score)
        if prefer is not None and prefer.alive:
            candidates = [prefer] + [
                h for h in candidates if h is not prefer
            ]
        for h in candidates:
            try:
                h.call("submit", request=dict(request_json), rid=rid)
            except QueueFull:
                continue
            except HostDown:
                self._declare_down(h.host_id, "submit RPC failed")
                continue
            except (ValueError, KeyError) as e:
                if t is not None:
                    t.status = FAILED
                    t.error = f"{type(e).__name__}: {e}"
                    t.finished_at = time.perf_counter()
                return
            if t is not None:
                t.host = h.host_id
                t.status = QUEUED
            h.health["queue_depth"] += 1
            return
        self._limbo.append({"rid": rid, "request": dict(request_json)})

    def _drain_limbo(self) -> None:
        if not self._limbo:
            return
        pending, self._limbo = self._limbo, []
        if not self._live():
            for item in pending:
                t = self.tickets.get(item["rid"])
                if t is not None and t.status not in _TERMINAL:
                    t.status = FAILED
                    t.error = (
                        "every cluster host is down; request cannot "
                        "be placed"
                    )
                    t.finished_at = time.perf_counter()
            return
        for item in pending:
            if self.tickets.get(item["rid"], None) is not None and \
                    self.tickets[item["rid"]].status in _TERMINAL:
                continue  # cancelled while in limbo
            self._place(item["rid"], item["request"])

    # -- whole-host failover -------------------------------------------------

    def down_host(self, host_id: int, reason: str = "operator") -> None:
        """Operator call: declare a host down NOW — kill it (a real
        SIGKILL for spawned workers), drain it from routing, and fail
        its WAL-known work over to the survivors. The whole-host
        analogue of ``SimServer.quarantine_device``."""
        if host_id not in self.hosts:
            raise KeyError(f"unknown host {host_id!r}")
        h = self.hosts[host_id]
        if not h.alive and host_id in self._dead_events:
            return  # already down and failed over
        h.kill()
        self._declare_down(host_id, reason)

    def _declare_down(self, host_id: int, reason: str) -> None:
        h = self.hosts.get(host_id)
        if h is None or (not h.alive and host_id in self._dead_events):
            return
        h.kill()  # idempotent; stops a half-dead worker writing
        self._metrics.inc("hosts_down")
        self.trace.instant(
            "cluster.host_down", host=host_id, reason=reason,
        )
        try:
            events = read_events(h.wal_dir)
        except FileNotFoundError:
            events = []
        self._dead_events[host_id] = events
        order, recs, retired, streamed, holds, released = (
            classify_events(events)
        )
        undone = unfinished(order, retired, streamed)
        todo: List[str] = []
        for rid in undone:
            t = self.tickets.get(rid)
            if t is None or t.host != host_id:
                continue  # stolen away earlier, or not ours
            if t.status in _TERMINAL and t.streamed_at:
                continue
            todo.append(rid)
        # requests the WAL attests FINISHED (retire + streamed for
        # DONE) whose head mirror is stale — the kill can land between
        # the worker's durable write and this router's next poll:
        # finalize them from the WAL truth, never re-run them
        for rid in order:
            t = self.tickets.get(rid)
            if (
                t is None or t.host != host_id or rid in undone
                or rid not in retired
            ):
                continue
            fin = retired[rid]
            t.status = str(fin.get("status"))
            t.error = fin.get("error") or t.error
            if rid in streamed and t.streamed_at is None:
                t.streamed_at = time.perf_counter()
            if t.result_path is None:
                path = os.path.join(self.out_dir, f"{rid}.lens")
                if os.path.exists(path):
                    t.result_path = path
            if t.finished_at is None:
                t.finished_at = time.perf_counter()
        # DONE requests with live holds re-adopt too (their spill in
        # the shared tier keeps resubmit chains alive across the loss)
        for rid in order:
            t = self.tickets.get(rid)
            if (
                rid in holds and rid not in released
                and rid not in todo
                and t is not None and t.host == host_id
                and t.status == DONE
                and t.request.hold_state
            ):
                todo.append(rid)
        for rid in todo:
            t = self.tickets[rid]
            t.host = None
            self._rid_dead_host[rid] = host_id
            if t.status not in _TERMINAL or not t.streamed_at:
                t.status = QUEUED
                t.streamed_at = None
                t.result_path = None
                t._fail_epochs += 1
                t.requeues += 1
        self._displaced.extend(todo)
        self._drain_displaced()

    def _drain_displaced(self) -> None:
        if not self._displaced:
            return
        pending, self._displaced = self._displaced, []
        survivors = sorted(self._live(), key=self._score)
        if not survivors:
            for rid in pending:
                t = self.tickets.get(rid)
                if t is not None and t.status not in _TERMINAL:
                    t.status = FAILED
                    t.error = (
                        "every cluster host is down; displaced "
                        "request cannot be re-queued"
                    )
                    t.finished_at = time.perf_counter()
            return
        # spread the displaced work over survivors by load, round
        # robin from the emptiest — batched into ONE adopt RPC per
        # (survivor, dead host): the events payload is the dead host's
        # whole WAL, so per-rid calls would reship and re-classify it
        # N times during exactly the window survivors are absorbing
        # the dead host's load
        groups: Dict[tuple, List[str]] = {}
        for i, rid in enumerate(pending):
            t = self.tickets.get(rid)
            if t is None or (
                t.status in _TERMINAL and not t.request.hold_state
            ):
                continue
            h = survivors[i % len(survivors)]
            dead = self._rid_dead_host.get(rid)
            groups.setdefault((h.host_id, dead), []).append(rid)
        for (host_id, dead), rids in groups.items():
            h = self.hosts[host_id]
            events = self._dead_events.get(dead, [])
            if not h.alive:
                self._displaced.extend(rids)
                continue
            try:
                h.call(
                    "adopt", events=events, rids=rids,
                    timeout=self.rpc_timeout_s,
                )
            except HostDown:
                self._declare_down(h.host_id, "adopt RPC failed")
                self._displaced.extend(rids)
                continue
            except (ValueError, KeyError):
                # one bad rid refused the batch MID-application (the
                # worker adopts in order): retry one by one so it
                # cannot take its batchmates down
                self._adopt_one_by_one(h, dead, events, rids)
                continue
            for rid in rids:
                self._mark_adopted(rid, dead, h)

    def _adopt_one_by_one(
        self, h: _Host, dead: Optional[int],
        events: List[Dict[str, Any]], rids: List[str],
    ) -> None:
        """Per-rid adoption fallback after a refused batch — the old
        (round-17-initial) granularity, where one continuation with a
        lost spill fails alone. A rid the partial batch already
        adopted answers with the duplicate-adoption refusal, which IS
        adoption."""
        for j, rid in enumerate(rids):
            try:
                h.call(
                    "adopt", events=events, rids=[rid],
                    timeout=self.rpc_timeout_s,
                )
            except HostDown:
                self._declare_down(h.host_id, "adopt RPC failed")
                self._displaced.extend(rids[j:])
                return
            except (ValueError, KeyError) as e:
                if "duplicate adoption" not in str(e):
                    t = self.tickets[rid]
                    t.status = FAILED
                    t.error = (
                        f"failover adoption failed: "
                        f"{type(e).__name__}: {e}"
                    )
                    t.finished_at = time.perf_counter()
                    continue
            self._mark_adopted(rid, dead, h)

    def _mark_adopted(
        self, rid: str, dead: Optional[int], h: _Host
    ) -> None:
        t = self.tickets[rid]
        t.host = h.host_id
        self._metrics.inc("requeued")
        h.health["queue_depth"] += 1
        self.trace.instant(
            "cluster.failover", rid=rid,
            src=dead, dst=h.host_id,
        )

    def _adopt_finished(self, t: ClusterTicket) -> _Host:
        """Re-home a FINISHED ticket (held parent) from a dead host
        onto the best survivor, for resubmit-after-failover."""
        dead = (
            t.host if t.host is not None
            else self._rid_dead_host.get(t.request_id)
        )
        events = self._dead_events.get(dead)
        if events is None:
            raise ValueError(
                f"request {t.request_id}'s host {dead} is gone and "
                f"left no readable WAL; cannot re-home it"
            )
        survivors = sorted(self._live(), key=self._score)
        if not survivors:
            raise ValueError("every cluster host is down")
        h = survivors[0]
        h.call(
            "adopt", events=events, rids=[t.request_id],
            timeout=self.rpc_timeout_s,
        )
        t.host = h.host_id
        self._rid_dead_host.pop(t.request_id, None)
        return h

    # -- observability -------------------------------------------------------

    def _gauges(self) -> Dict[str, Any]:
        live = self._live()
        counters = self._summed_counters()
        busy = counters.get("lane_windows_busy", 0)
        total = counters.get("lane_windows_total", 0)
        return {
            "occupancy": (busy / total) if total else None,
            "queue_depth": len(self.queue),
            "lanes_busy": sum(
                h.health["lanes_busy"] for h in live
            ),
            "lanes_total": sum(
                h.health["lanes_total"] for h in live
            ),
            "quarantined_devices": sum(
                h.health.get("quarantined_devices", 0) for h in live
            ),
            "hosts_alive": len(live),
            "hosts_down": sorted(
                h.host_id
                for h in self.hosts.values()
                if not h.alive
            ),
            **(
                {
                    "results": {
                        "entries": len(self._result_cache),
                        "bytes": self._result_cache.total_bytes(),
                        "router_hits": (
                            self._metrics.counters["result_hits"]
                        ),
                        "router_misses": (
                            self._metrics.counters["result_misses"]
                        ),
                    }
                }
                if self._result_cache is not None
                else {}
            ),
        }

    def _summed_counters(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for h in self.hosts.values():
            for k, v in h.health.get("counters", {}).items():
                out[k] = out.get(k, 0) + int(v)
        return out

    def cluster_info(self) -> Dict[str, Any]:
        """Per-host identity + health for ``/healthz`` in cluster
        mode (docs/serving.md, "Cluster serving")."""
        head = self._metrics.counters
        return {
            "hosts": [
                {
                    "host": h.host_id,
                    "alive": h.alive,
                    "state": "serving" if h.alive else "down",
                    "queue_depth": h.health["queue_depth"],
                    "lanes_busy": h.health["lanes_busy"],
                    "lanes_total": h.health["lanes_total"],
                    "stolen": h.health.get("counters", {}).get(
                        "stolen", 0
                    ),
                    "adopted": h.health.get("counters", {}).get(
                        "adopted", 0
                    ),
                }
                for h in self.hosts.values()
            ],
            "hosts_alive": len(self._live()),
            "hosts_down": sorted(
                h.host_id for h in self.hosts.values() if not h.alive
            ),
            "stolen": head["stolen"],
            "requeued": head["requeued"],
        }

    def metrics(self) -> Dict[str, Any]:
        """The cluster-wide live snapshot: summed worker counters
        (plus the router's own routing/stealing/failover counters
        under distinct names), cluster gauges, and one row per host."""
        counters = self._summed_counters()
        for k, v in self._metrics.counters.items():
            if k in (
                "stolen", "requeued", "ticks",
                "result_hits", "result_misses",
            ):
                counters[f"router_{k}"] = v
            elif k == "hosts_down":
                counters[k] = v
        gauges = self._gauges()
        tenants: Dict[str, Dict[str, int]] = {}
        for src in [self._metrics.tenants] + [
            h.health.get("tenants", {}) for h in self.hosts.values()
        ]:
            for name, row in (src or {}).items():
                agg = tenants.setdefault(name, {})
                for k, v in row.items():
                    agg[k] = agg.get(k, 0) + int(v)
        live = self._live()
        return {
            **gauges,
            "counters": counters,
            "retraces": sum(
                h.health.get("retraces", 0) for h in live
            ),
            "snapshots_resident": sum(
                h.health.get("snapshots_resident", 0) for h in live
            ),
            "snapshot_bytes": sum(
                h.health.get("snapshot_bytes", 0) for h in live
            ),
            "latency_seconds": {"p50": None, "p95": None, "p99": None},
            "tenants": tenants,
            "hosts": [
                {
                    "host": h.host_id,
                    "alive": h.alive,
                    "queue_depth": h.health["queue_depth"],
                    "lanes_busy": h.health["lanes_busy"],
                    "lanes_total": h.health["lanes_total"],
                    "counters": dict(h.health.get("counters", {})),
                }
                for h in self.hosts.values()
            ],
            "cluster": self.cluster_info(),
        }

    def prometheus_metrics(self) -> str:
        """Cluster exposition: router counters plus per-host gauges
        and counters, every per-host sample carrying a ``host``
        label — the end-to-end attribution the multi-host view
        needs."""
        lines: List[str] = []

        def emit(name, kind, help_, samples):
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            lines.extend(samples)

        head = self._metrics.counters
        emit(
            "lens_cluster_hosts_alive", "gauge",
            "live hosts in the serving cluster",
            [f"lens_cluster_hosts_alive {len(self._live())}"],
        )
        for name, help_ in (
            ("stolen", "queued requests migrated by work-stealing"),
            ("requeued", "requests re-queued by whole-host failover"),
            ("hosts_down", "hosts declared down"),
            ("submitted", "requests routed by this router"),
            ("rejected", "submits refused cluster-wide"),
            ("result_hits",
             "submits answered at the router from the result cache"),
            ("result_misses",
             "router result-cache lookups that missed"),
        ):
            emit(
                f"lens_cluster_{name}_total", "counter", help_,
                [f"lens_cluster_{name}_total {head[name]}"],
            )
        for gauge, help_ in (
            ("queue_depth", "queued requests on the host"),
            ("lanes_busy", "occupied lanes on the host"),
            ("lanes_total", "schedulable lanes on the host"),
        ):
            emit(
                f"lens_cluster_host_{gauge}", "gauge",
                f"{help_} (label: host)",
                [
                    f'lens_cluster_host_{gauge}'
                    f'{{host="{h.host_id}"}} '
                    f'{h.health[gauge]}'
                    for h in self.hosts.values()
                ],
            )
        emit(
            "lens_cluster_host_up", "gauge",
            "1 while the host serves, 0 once drained (label: host)",
            [
                f'lens_cluster_host_up{{host="{h.host_id}"}} '
                f'{1 if h.alive else 0}'
                for h in self.hosts.values()
            ],
        )
        for counter in ("submitted", "retired", "stolen", "adopted",
                        "recovered", "requeued", "diverged",
                        "result_hits", "suffix_coalesced"):
            samples = [
                f'lens_cluster_host_{counter}_total'
                f'{{host="{h.host_id}"}} '
                f'{h.health.get("counters", {}).get(counter, 0)}'
                for h in self.hosts.values()
            ]
            emit(
                f"lens_cluster_host_{counter}_total", "counter",
                f"per-host {counter} (label: host)", samples,
            )
        # the router's own door-side metrics (tenant counters ride
        # here in front-door deployments)
        lines.append(self._metrics.prometheus_text())
        return "\n".join(lines) + "\n"

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        first_error: Optional[BaseException] = None
        for h in self.hosts.values():
            try:
                h.shutdown()
            except BaseException as e:
                first_error = first_error or e
        try:
            meta = {
                "cluster": self.cluster_info(),
                "metrics": {
                    k: v for k, v in self.metrics().items()
                    if k != "hosts"
                },
                "hosts": self.n_hosts,
                "out_dir": self.out_dir,
            }
            with open(
                os.path.join(self.cluster_dir, "cluster_meta.json"),
                "w",
            ) as f:
                json.dump(meta, f, indent=1, default=str)
        except BaseException as e:
            first_error = first_error or e
        try:
            self.trace.close()
        except BaseException as e:
            first_error = first_error or e
        if first_error is not None:
            raise first_error

    def __enter__(self) -> "ClusterServer":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        try:
            self.close()
        except BaseException:
            if exc_type is None:
                raise
