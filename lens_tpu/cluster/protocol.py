"""Cluster wire protocol: length-prefixed JSON messages over TCP.

One frame = a 4-byte big-endian payload length + UTF-8 JSON. Requests
are ``{"op": ..., **params}``; replies are ``{"ok": true, **result}``
or ``{"ok": false, "error_type": ..., "error": ..., ...}`` — the
error envelope round-trips the serve layer's typed exceptions
(``QueueFull`` keeps its retry-after hint, ``RequestValidationError``
its machine-readable field path) so the router re-raises exactly what
an in-process ``SimServer`` call would have raised.

Deliberately minimal: localhost TCP is the simulated-hosts transport
this box can actually test, and the frame layout is transport-agnostic
enough that a real deployment can carry it over whatever its hosts
already speak (the jax.distributed bring-up in
``lens_tpu.parallel.distributed`` solves identity, not serving RPC).
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Mapping, Optional

from lens_tpu.serve.batcher import (
    QueueFull,
    RequestValidationError,
    SimulationDiverged,
)
from lens_tpu.serve.streamer import WatchdogTimeout

_LEN = struct.Struct(">I")

#: Refuse frames past this (a corrupt length prefix must not look like
#: a multi-GiB allocation). WAL adoption payloads are the largest real
#: message: thousands of events, still far under this.
MAX_FRAME = 256 * 2**20


def send_msg(sock: socket.socket, obj: Mapping[str, Any]) -> None:
    payload = json.dumps(obj, default=str).encode()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> Dict[str, Any]:
    """One frame, honoring the socket's own timeout (``socket.timeout``
    propagates — the router's heartbeat-loss signal)."""
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > MAX_FRAME:
        raise ConnectionError(f"frame length {n} exceeds {MAX_FRAME}")
    return json.loads(_recv_exact(sock, n).decode())


#: Exception types that cross the wire by name. Anything else arrives
#: as RuntimeError carrying the original type in its message.
_ERRORS = {
    "QueueFull": QueueFull,
    "RequestValidationError": RequestValidationError,
    "SimulationDiverged": SimulationDiverged,
    "WatchdogTimeout": WatchdogTimeout,
    "ValueError": ValueError,
    "KeyError": KeyError,
    "FileNotFoundError": FileNotFoundError,
}


def encode_error(exc: BaseException) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "ok": False,
        "error_type": type(exc).__name__,
        "error": str(exc),
    }
    if isinstance(exc, QueueFull):
        out["retry_after"] = float(exc.retry_after)
        out["depth"] = int(getattr(exc, "depth", 0))
    if isinstance(exc, RequestValidationError):
        out["path"] = exc.path
    return out


def raise_error(reply: Mapping[str, Any]) -> None:
    """Re-raise a worker-side error head-side, typed."""
    name = reply.get("error_type", "RuntimeError")
    message = reply.get("error", "worker error")
    if name == "QueueFull":
        raise QueueFull(
            float(reply.get("retry_after", 1.0)),
            int(reply.get("depth", 0)),
        )
    if name == "RequestValidationError":
        raise RequestValidationError(message, path=reply.get("path"))
    cls = _ERRORS.get(name)
    if cls is KeyError:
        # KeyError str()s to its repr'd key; rewrap cleanly
        raise KeyError(message)
    if cls is not None:
        raise cls(message)
    raise RuntimeError(f"{name}: {message}")


def rpc(
    sock: socket.socket,
    op: str,
    timeout: Optional[float] = None,
    **params: Any,
) -> Dict[str, Any]:
    """One request/reply exchange. ``timeout`` bounds the whole
    exchange (None = the socket's current default); worker-side errors
    re-raise typed, transport errors propagate as
    ``ConnectionError``/``socket.timeout`` for the router's health
    logic to interpret."""
    prev = sock.gettimeout()
    if timeout is not None:
        sock.settimeout(timeout)
    try:
        send_msg(sock, {"op": op, **params})
        reply = recv_msg(sock)
    finally:
        if timeout is not None:
            sock.settimeout(prev)
    if not reply.get("ok"):
        raise_error(reply)
    return reply
