"""The per-host serve worker: one ``SimServer`` per host, behind RPC.

A worker owns everything host-local — its resident lane pools (and
device mesh), its shard-keyed snapshot tiers, its per-host WAL
directory — and exposes the serve client surface over two localhost
TCP connections to the cluster router (docs/serving.md, "Cluster
serving"):

- **control**: submit/withdraw/adopt/cancel/status/result/metrics —
  every op that touches scheduler state, serialized with the tick
  thread through one lock (the front door's proven threading model).
- **health**: ping/poll answered LOCK-FREE from a snapshot the tick
  thread publishes after every tick — a worker mid-compile (the first
  window of a bucket can stall tens of seconds on this box) still
  answers heartbeats instantly, so a slow compile is never mistaken
  for a dead host.

Identity: the router passes ``host_id`` in the worker config
(simulated-hosts mode); a config with ``"distributed": true`` instead
derives it from the jax.distributed runtime via
:func:`lens_tpu.parallel.distributed.cluster_identity` — the real
multi-host bring-up path, which this box cannot exercise beyond the
single-process fallback.

Request ids: the ROUTER mints every client rid; the worker's own mint
is offset to ``10_000_000 * (host_id + 1)`` so server-internal tickets
(prefix runs, warm scavengers) can never collide with router-minted
ids — or with another host's internals after a failover adoption.
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from typing import Any, Dict, List, Mapping, Optional

from lens_tpu.cluster.protocol import encode_error, recv_msg, send_msg
from lens_tpu.serve.metrics import request_timing_row

#: Worker-internal id mint spacing per host (see module docstring).
ID_SPAN = 10_000_000

_REQ_RE = re.compile(r"^req-(\d+)$")


def _offset_ids(server: Any, offset: int) -> None:
    """Advance the worker's id mint past ``offset`` AND past every id
    its recovery replayed (the mint must never move backwards)."""
    top = int(offset)
    for rid in server.tickets:
        m = _REQ_RE.match(rid)
        if m:
            top = max(top, int(m.group(1)) + 1)
    server.queue.skip_ids(top)


def _ticket_row(t: Any) -> Dict[str, Any]:
    """The per-ticket facts the router mirrors into its own table."""
    return {
        "rid": t.request_id,
        "status": t.status,
        "error": t.error,
        "steps_done": int(t.steps_done),
        "horizon_steps": int(t.horizon_steps),
        "result_path": t.result_path,
        "streamed": t.streamed_at is not None,
        "requeues": int(t.requeues),
        "diverged": bool(t.diverged),
        "parent": t.parent,
        "priority": t.request.priority,
    }


class WorkerCore:
    """Op dispatch + tick loop over one ``SimServer``.

    Shared by the subprocess worker (ops arrive over TCP) and the
    router's in-process simulated hosts (ops arrive as direct calls,
    JSON-roundtripped for wire parity) — the routing/stealing/failover
    logic is therefore testable without spawning processes, while the
    drills exercise the identical dispatch through real sockets.
    """

    #: Publish cadence while the scheduler is busy: the snapshot is
    #: advisory routing/health state, and rebuilding every ticket row
    #: at full tick rate is measurable CPU the windows want (the
    #: router polls far slower than the server ticks anyway). State
    #: CHANGES the router acts on (submit/cancel/adopt/withdraw)
    #: publish immediately, bypassing the throttle.
    PUBLISH_EVERY_S = 0.01
    #: Idle refresh cadence: one publish the moment the scheduler
    #: settles, then a slow heartbeat-refresh to catch stamps that can
    #: land just after the final tick (the streamer thread's durable
    #: mark). Rebuilding the ticket table every 2 ms idle-loop pass
    #: would both burn CPU and bump the version each time, so a
    #: router poll could never come back ``unchanged``.
    IDLE_PUBLISH_EVERY_S = 0.25

    def __init__(self, server: Any, host_id: int):
        if server.sink != "log":
            raise ValueError(
                "cluster workers need sink='log': results must be "
                "host-crossing files, not process memory"
            )
        self.server = server
        self.host_id = int(host_id)
        self.lock = threading.RLock()
        self.error: Optional[BaseException] = None
        self._version = 0
        self._published: Dict[str, Any] = {}
        self._published_at = 0.0
        self._content: Dict[str, Any] = {}
        self._settled = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.idle_sleep_s = 0.002
        # pipeline on: a busy tick can return without blocking (all
        # lanes mid-window, stream pipe not full) and the loop would
        # spin a whole core against the windows' own compute — pace
        # it. pipeline off: tick blocks through the window inline, so
        # only a short yield is left (on an oversubscribed box the
        # explicit sleep is the OS's rotation point between workers).
        self.busy_sleep_s = (
            0.001 if getattr(server, "pipeline", "on") == "on"
            else 0.0005
        )
        self.publish()

    # -- tick thread ---------------------------------------------------------

    def start(self) -> "WorkerCore":
        self._thread = threading.Thread(
            target=self._loop, name=f"cluster-worker-{self.host_id}",
            daemon=True,
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            busy = self.tick_once()
            if not busy:
                time.sleep(self.idle_sleep_s)
            elif self.busy_sleep_s:
                # pace the tick loop while windows compute: a busy
                # spin here (the in-process driving style) would burn
                # a whole core PER WORKER against the windows' own
                # compute threads — on a small box that is measurable
                # aggregate throughput. Windows run ~10ms+; sub-ms
                # pacing costs <5% dispatch latency and frees the core
                time.sleep(self.busy_sleep_s)

    def tick_once(self) -> bool:
        """One scheduler tick + snapshot publish. A fatal server error
        (parked stream failure, watchdog) parks on ``self.error`` — the
        published health names it, which the router reads as this host
        failing, and every later control op refuses with the cause."""
        with self.lock:
            if self.error is not None:
                return False
            try:
                busy = self.server.tick()
            except BaseException as e:
                self.error = e
                self.publish()
                return False
            now = time.perf_counter()
            if busy:
                self._settled = False
                if now - self._published_at >= self.PUBLISH_EVERY_S:
                    self.publish()
            elif not self._settled or now - self._published_at \
                    >= self.IDLE_PUBLISH_EVERY_S:
                self.publish()
                self._settled = True
        return busy or len(self.server.queue) > 0 or self._streaming()

    def _streaming(self) -> bool:
        """Stream/publish work still in flight after the scheduler
        settles: windows queued behind the stream thread, or completed
        logs waiting to be filed into the shared result cache. Counting
        these as busy keeps the local-mode router ticking until every
        result is durably published — the same "idle = fully streamed"
        contract ``SimServer.run_until_idle`` enforces for itself — so
        a repeat submit right after idle can hit the cache instead of
        recomputing."""
        srv = self.server
        if getattr(srv, "_cache_pending", None):
            return True
        s = srv._streamer
        return s is not None and any(s.progress_token())

    def publish(self) -> None:
        """Refresh the lock-free health/ticket snapshot (caller holds
        the lock, or owns the server single-threadedly)."""
        srv = self.server
        m = srv._metrics
        self._published_at = time.perf_counter()
        m.queue_depth = len(srv.queue)
        busy_lanes = sum(b.busy() for b in srv.buckets.values())
        content = {
            "host": self.host_id,
            "alive": self.error is None,
            "error": (
                f"{type(self.error).__name__}: {self.error}"
                if self.error is not None else None
            ),
            "queue_depth": len(srv.queue),
            "lanes_busy": busy_lanes,
            "lanes_total": sum(
                b.lanes_total() for b in srv.buckets.values()
            ),
            "free_lanes": sum(
                b.free_lanes() for b in srv.buckets.values()
            ),
            "busy": busy_lanes > 0 or len(srv.queue) > 0,
            "retry_after": float(srv.retry_after_hint()),
            "quarantined_devices": len(srv._quarantined),
            "retraces": sum(
                s.pool.retraces()
                for b in srv.buckets.values()
                for s in b.shards
            ),
            "snapshots_resident": len(srv.snapshots),
            "snapshot_bytes": int(srv.snapshots.resident_bytes()),
            # copies, not live references (both properties copy): the
            # dedup below compares against the previous snapshot, so
            # shared mutable state would read as "unchanged" forever
            "tenants": m.tenants,
            "counters": dict(m.counters),
            "tickets": [
                _ticket_row(t)
                for t in srv.tickets.values()
                if not t.internal
            ],
        }
        if self._same_but_ticks(content, self._content):
            # nothing moved: keep the version stable so the router's
            # since= poll comes back "unchanged" (version-only bumps
            # would ship the full ticket table on every heartbeat)
            return
        self._content = content
        self._version += 1
        self._published = {"version": self._version, **content}

    @staticmethod
    def _same_but_ticks(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
        """Snapshot equality, ignoring the scheduler's ``ticks``
        counter: an idle server still ticks, and republishing the full
        ticket table because ONLY the tick count moved defeats the
        whole ``unchanged`` poll path (the advertised count going
        slightly stale while idle is harmless — it is advisory)."""

        def norm(c: Dict[str, Any]) -> Dict[str, Any]:
            counters = dict(c.get("counters") or {})
            counters.pop("ticks", None)
            return {**c, "counters": counters}

        return bool(b) and norm(a) == norm(b)

    # -- health surface (lock-free) ------------------------------------------

    def handle_health(self, msg: Mapping[str, Any]) -> Dict[str, Any]:
        op = msg.get("op")
        snap = self._published  # one reference read: never torn
        if op == "ping":
            return {
                "ok": True,
                **{k: v for k, v in snap.items() if k != "tickets"},
            }
        if op == "poll":
            if msg.get("since") == snap["version"]:
                return {
                    "ok": True, "version": snap["version"],
                    "unchanged": True,
                }
            return {"ok": True, **snap}
        return {
            "ok": False, "error_type": "ValueError",
            "error": f"unknown health op {op!r}",
        }

    # -- control surface -----------------------------------------------------

    def handle_control(self, msg: Mapping[str, Any]) -> Dict[str, Any]:
        try:
            with self.lock:
                return {"ok": True, **self._dispatch(msg)}
        except BaseException as e:  # typed across the wire
            return encode_error(e)

    def _dispatch(self, msg: Mapping[str, Any]) -> Dict[str, Any]:
        op = msg.get("op")
        srv = self.server
        if self.error is not None and op not in ("shutdown", "hello"):
            raise RuntimeError(
                f"worker host {self.host_id} scheduler died: "
                f"{type(self.error).__name__}: {self.error}"
            )
        if op == "hello":
            return self.info()
        if op == "validate":
            srv.validate(msg["request"])
            return {}
        if op == "submit":
            rid = srv.submit(msg["request"], rid=msg.get("rid"))
            self.publish()
            return {"rid": rid}
        if op == "resubmit":
            rid = srv.resubmit(
                msg["rid"], float(msg["extra_horizon"])
            )
            self.publish()
            return {"rid": rid}
        if op == "release":
            srv.release_state(msg["rid"])
            return {}
        if op == "cancel":
            status = srv.cancel(msg["rid"])
            self.publish()
            return {"status": status}
        if op == "status":
            out = srv.status(msg["rid"])
            t = srv.tickets[msg["rid"]]
            out["timing"] = request_timing_row(t, srv._metrics._t0)
            out["streamed"] = t.streamed_at is not None
            out["requeues"] = int(t.requeues)
            out["host"] = self.host_id
            return out
        if op == "result":
            # log sink: result() drains this rid's stream, then hands
            # back the (shared-filesystem) log path
            return {"path": srv.result(msg["rid"])}
        if op == "withdraw":
            return {"requests": self._withdraw_batch(
                int(msg.get("count", 1))
            )}
        if op == "adopt":
            adopted = srv.adopt_displaced(
                msg["events"], list(msg["rids"])
            )
            self.publish()
            return {"adopted": adopted}
        if op == "prewarm":
            srv.prewarm(msg["spec"])
            return {}
        if op == "metrics":
            return {"metrics": srv.metrics()}
        if op == "prometheus":
            return {"text": srv.prometheus_metrics()}
        if op == "shutdown":
            return {}
        raise ValueError(f"unknown control op {op!r}")

    def _withdraw_batch(self, count: int) -> List[Dict[str, Any]]:
        """Withdraw up to ``count`` STEALABLE queued requests, youngest
        first (the tail of the FIFO is the work least likely to start
        soon — stealing it disturbs admission order least). Ineligible
        tickets (running, waiting on a prefix, continuations, ...) are
        skipped, not errors: the router asked for whatever can move."""
        out: List[Dict[str, Any]] = []
        for t in reversed(list(self.server.queue)):
            if len(out) >= count:
                break
            rid = t.request_id
            try:
                request = self.server.withdraw(rid)
            except (ValueError, KeyError):
                continue
            out.append({"rid": rid, "request": request})
        if out:
            self.publish()
        return out

    def info(self) -> Dict[str, Any]:
        srv = self.server
        return {
            "host": self.host_id,
            "pid": os.getpid(),
            "buckets": sorted(srv.buckets),
            "fingerprint": srv._fingerprint,
            "lanes_total": sum(
                b.lanes_total() for b in srv.buckets.values()
            ),
            "queue_depth_max": srv.queue.max_depth,
            "out_dir": srv.out_dir,
        }

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60)
        self.server.close()


# -- subprocess entry (python -m lens_tpu cluster-worker) --------------------


def _build_server(cfg: Mapping[str, Any]):
    from lens_tpu.serve import FaultPlan, SimServer

    kwargs = dict(cfg.get("server") or {})
    faults = kwargs.pop("faults", None)
    if faults is not None:
        kwargs["faults"] = FaultPlan.from_spec(faults)
    return SimServer(cfg["buckets"], **kwargs)


def run_worker(config_path: str) -> int:
    """Worker process main: build the host's ``SimServer`` from the
    JSON config the router wrote, dial the router's control + health
    connections, and serve ops until shutdown (or until the router
    goes away — a worker never outlives its head)."""
    with open(config_path) as f:
        cfg = json.load(f)
    host_id = cfg.get("host_id")
    if cfg.get("distributed"):
        # real multi-host bring-up: join the jax.distributed runtime
        # and take identity from it when the config does not pin one
        from lens_tpu.parallel.distributed import (
            cluster_identity,
            initialize,
        )

        initialize()
        if host_id is None:
            host_id, _ = cluster_identity()
    host_id = int(host_id)
    server = _build_server(cfg)
    if cfg.get("meta_dir"):
        server.meta_dir = cfg["meta_dir"]
    _offset_ids(server, ID_SPAN * (host_id + 1))
    if server.trace:
        # every span/instant this worker emits carries its host label
        server.trace.extra = {"host": host_id}
    core = WorkerCore(server, host_id)
    addr = (cfg.get("join_host", "127.0.0.1"), int(cfg["join_port"]))
    control = socket.create_connection(addr, timeout=60)
    send_msg(control, {
        "op": "hello", "role": "control", "host_id": host_id,
        **core.info(),
    })
    recv_msg(control)  # router ack
    health = socket.create_connection(addr, timeout=60)
    send_msg(health, {
        "op": "hello", "role": "health", "host_id": host_id,
    })
    recv_msg(health)
    control.settimeout(None)
    health.settimeout(None)
    core.start()

    def _health_loop() -> None:
        try:
            while True:
                msg = recv_msg(health)
                send_msg(health, core.handle_health(msg))
        except (ConnectionError, OSError, ValueError):
            pass  # router gone; the control loop owns shutdown

    threading.Thread(
        target=_health_loop, name="cluster-health", daemon=True
    ).start()
    rc = 0
    try:
        while True:
            try:
                msg = recv_msg(control)
            except (ConnectionError, OSError):
                break  # router died: shut down cleanly
            reply = core.handle_control(msg)
            try:
                send_msg(control, reply)
            except (ConnectionError, OSError):
                break
            if msg.get("op") == "shutdown":
                break
    finally:
        try:
            core.close()
        except BaseException as e:
            print(f"cluster-worker: close error: {e}", flush=True)
            rc = 1
    return rc
