"""lens_tpu.cluster: multi-host serving — one serve worker per host
behind a locality-aware router with work-stealing and whole-host
failover.

The mesh scheduler (``SimServer(mesh=N)``) scales to every device in
one process; this package scales past the process. Each HOST runs one
worker — its own process with its own :class:`~lens_tpu.serve.SimServer`
(mesh, snapshot tiers, per-host WAL directory) — and a
:class:`ClusterServer` routes requests across them: placement scores
queue depth and snapshot locality, work-stealing migrates queued
requests off a backed-up host's FIFO, and a host that dies (heartbeat
loss, a ``FaultPlan`` ``host_down``, a real SIGKILL) is drained from
routing while its WAL-known unfinished work re-queues onto survivors
under original ids. See docs/serving.md, "Cluster serving".

The architectural reference is Podracer's Sebulba split (PAPERS.md):
independent per-host actors behind a thin central work source, with
per-host state kept host-local and only routing/health crossing hosts.
"""

from lens_tpu.cluster.router import ClusterServer, HostDown
from lens_tpu.cluster.worker import WorkerCore, run_worker

__all__ = [
    "ClusterServer",
    "HostDown",
    "WorkerCore",
    "run_worker",
]
