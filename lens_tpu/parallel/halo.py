"""Halo-exchange diffusion: the spatially sharded stencil.

Each device owns a horizontal strip ``[M, H/n, W]`` of the field. Every
FTCS substep needs one row of neighbor data on each side, exchanged with
``lax.ppermute`` over the ``space`` mesh axis — the rebuild's moral
equivalent of context/sequence-parallel ring exchange (SURVEY.md §5
"long-context"), and the explicit-collective replacement for the halo
traffic XLA inserts on the auto-partitioned path.

Global boundaries stay Neumann (edge-clamped), matching
``ops.diffusion._neumann_laplacian`` bit-for-bit: the first/last shard
substitutes its own edge row for the missing halo.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def diffuse_halo(
    strip: jnp.ndarray,
    alpha: jnp.ndarray,
    n_substeps: int,
    axis_name: str,
    n_shards: int,
) -> jnp.ndarray:
    """FTCS substeps on a field strip with ppermute halo exchange.

    Must run inside shard_map with ``axis_name`` a mesh axis of size
    ``n_shards`` (static). strip: [M, H_local, W]; alpha: [M].

    Strips are ordered by ``axis_index``: shard i owns global rows
    [i*H_local, (i+1)*H_local).
    """
    a = alpha.reshape(-1, 1, 1)
    idx = lax.axis_index(axis_name)
    send_down = [(i, i + 1) for i in range(n_shards - 1)]  # my last row -> i+1's top halo
    send_up = [(i + 1, i) for i in range(n_shards - 1)]    # my first row -> i-1's bottom halo

    def substep(_, f):
        if n_shards > 1:
            top_halo = lax.ppermute(f[:, -1:, :], axis_name, send_down)
            bottom_halo = lax.ppermute(f[:, :1, :], axis_name, send_up)
        else:
            top_halo = jnp.zeros_like(f[:, :1, :])
            bottom_halo = jnp.zeros_like(f[:, -1:, :])
        # Global Neumann boundary: edge shards clamp to their own edge row
        # (ppermute leaves non-receivers zero-filled, so overwrite).
        top_halo = jnp.where(idx == 0, f[:, :1, :], top_halo)
        bottom_halo = jnp.where(idx == n_shards - 1, f[:, -1:, :], bottom_halo)

        up = jnp.concatenate([top_halo, f[:, :-1, :]], axis=1)
        down = jnp.concatenate([f[:, 1:, :], bottom_halo], axis=1)
        left = jnp.concatenate([f[:, :, :1], f[:, :, :-1]], axis=2)
        right = jnp.concatenate([f[:, :, 1:], f[:, :, -1:]], axis=2)
        return f + a * (up + down + left + right - 4.0 * f)

    return lax.fori_loop(0, n_substeps, substep, strip)
