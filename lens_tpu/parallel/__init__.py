"""Distributed execution: device meshes, sharding specs, explicit SPMD.

The reference scales by spawning one OS process per cell and wiring them
through a Kafka/Zookeeper broker (reconstructed: ``lens/actor/shepherd.py``
+ actor topics, SURVEY.md §2 "distributed communication backend"). The
rebuild's backend is the TPU interconnect itself: a
``jax.sharding.Mesh`` with two logical axes —

- ``agents``: data parallelism over cells (the agent axis of every
  stacked state leaf is split across devices);
- ``space``: domain decomposition of the lattice (field rows split
  across devices, stencil halos exchanged with ``ppermute``).

A third scale dimension needs no collectives at all: the replicate axis
of a ``colony.Ensemble`` (``ShardedEnsemble``) — independent replicates
split across devices by XLA's batch partitioner, the framework's
perfect-scaling path for replicate statistics and parameter scans.

Collectives (``psum`` for global occupancy/exchange reduction,
``all_gather`` for field assembly, ``ppermute`` for halos) ride ICI
within a slice and DCN across slices — there is no broker tier at all.
"""

from lens_tpu.parallel.mesh import (
    colony_pspecs,
    make_mesh,
    mesh_shardings,
    multispecies_pspecs,
    spatial_pspecs,
)
from lens_tpu.parallel.halo import diffuse_halo
from lens_tpu.parallel.runner import ShardedSpatialColony
from lens_tpu.parallel.multispecies import ShardedMultiSpeciesColony
from lens_tpu.parallel.ensemble import ShardedEnsemble
from lens_tpu.parallel.distributed import (
    cluster_identity,
    coordinator_only,
    distribute,
    global_mesh,
    initialize,
    is_coordinator,
)

__all__ = [
    "make_mesh",
    "mesh_shardings",
    "colony_pspecs",
    "spatial_pspecs",
    "multispecies_pspecs",
    "diffuse_halo",
    "ShardedSpatialColony",
    "ShardedMultiSpeciesColony",
    "ShardedEnsemble",
    "initialize",
    "global_mesh",
    "distribute",
    "is_coordinator",
    "coordinator_only",
    "cluster_identity",
]
