"""ShardedSpatialColony: the explicit-collective SPMD colony step.

The same gather -> biology -> scatter -> division -> diffusion sequence as
``environment.spatial.SpatialColony.step`` (which replaces the reference's
Kafka exchange window, SURVEY.md §3.2), but written as a ``shard_map``
program over a 2D (agents x space) mesh with every cross-device movement
an explicit XLA collective:

- field strips assemble with ``all_gather`` over the space axis;
- bin occupancy and exchange deltas reduce with ``psum`` over the agent
  axis (global occupancy is what keeps shared-bin mass conservation
  exact across shards);
- diffusion halos move with ``ppermute`` (parallel.halo);
- division is per-shard: each device's block of rows has its own
  free-row pool, so row activation never crosses a shard boundary
  (capacity pressure is felt per shard, not globally — by design).

PRNG discipline: the ColonyState key stays replicated; every stochastic
use folds in the shard's ``axis_index`` so shards draw independent
streams while the stored key advances identically everywhere.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from lens_tpu.environment.spatial import (
    SpatialColony,
    SpatialState,
    apply_gather,
    exchange_payload,
    shared_view,
    zero_exchanges,
)
from lens_tpu.parallel.base import ShardedRunnerBase
from lens_tpu.parallel.mesh import (
    AGENTS_AXIS,
    SPACE_AXIS,
    mesh_shardings,
    spatial_pspecs,
    validate_divisible,
)
from lens_tpu.utils.dicts import get_path, set_path


class ShardedSpatialColony(ShardedRunnerBase):
    """Wraps a SpatialColony with a mesh-sharded step/run.

    The wrapped ``spatial`` provides all wiring (field ports, location
    path, share_bins) and the per-block biology; this class only owns the
    collectives. Deterministic composites produce trajectories equal to
    the unsharded path (tested); stochastic composites draw per-shard
    streams, so trajectories differ from unsharded by PRNG layout only.
    """

    def __init__(self, spatial: SpatialColony, mesh: Mesh):
        validate_divisible(
            spatial.colony.capacity, spatial.lattice.shape[0], mesh
        )
        super().__init__(mesh)
        self.spatial = spatial
        self.n_space = mesh.shape[SPACE_AXIS]

    # -- construction --------------------------------------------------------

    def initial_state(
        self, n_alive: int, key, stripe: bool = True, **kwargs
    ) -> SpatialState:
        """Build on host, then place per the mesh sharding layout.

        Placement goes through :func:`parallel.distributed.distribute`, so
        the same call works on a multi-host mesh (each host constructs the
        full state and keeps only its addressable shards).

        ``stripe`` (default) deals alive rows round-robin across agent
        shards (:func:`parallel.mesh.stripe_colony_rows`) so every
        shard's division pool starts equally loaded; pass False to keep
        the contiguous layout (e.g. to study per-shard saturation).
        """
        from lens_tpu.parallel.distributed import distribute
        from lens_tpu.parallel.mesh import stripe_colony_rows

        ss = self.spatial.initial_state(n_alive, key, **kwargs)
        if stripe:
            ss = ss._replace(
                colony=stripe_colony_rows(
                    ss.colony, self.mesh.shape[AGENTS_AXIS]
                )
            )
        return distribute(ss, self.mesh, spatial_pspecs(ss))

    # -- the SPMD step -------------------------------------------------------

    def _block_step(self, ss: SpatialState, timestep: float) -> SpatialState:
        """Per-device block program. Runs inside shard_map. Honors the
        wrapped spatial's ``coupling`` knob: the fused path mirrors
        ``SpatialColony._step_fused`` block for block (flat bin index
        derived once, occupancy + exchange as plan-driven segment-sums,
        raw view read off the single gather), the reference path keeps
        the original per-molecule program as the oracle."""
        if self.spatial.coupling == "fused":
            return self._block_step_fused(ss, timestep)
        return self._block_step_reference(ss, timestep)

    def _block_step_fused(
        self, ss: SpatialState, timestep: float
    ) -> SpatialState:
        """The fused coupling on a device mesh: the same CouplingPlan
        one-pass step as unsharded, with the two cross-shard reductions
        the coupling fundamentally needs — GLOBAL occupancy (psum of the
        per-block segment-sum over the agent axis, so shared-bin mass
        conservation spans shards) and the combined exchange delta (psum
        of per-block segment-sums, one clamp)."""
        spatial, lattice, colony = (
            self.spatial, self.spatial.lattice, self.spatial.colony
        )
        plan = spatial.plan
        cs, strip = ss.colony, ss.fields
        a_idx = lax.axis_index(AGENTS_AXIS)
        s_idx = lax.axis_index(SPACE_AXIS)
        full_fields = self._assemble_fields(strip, s_idx)  # [M, H, W]
        n_mols = len(lattice.molecules)
        ff = full_fields.reshape(n_mols, lattice.n_bins)
        locations = get_path(cs.agents, spatial.location_path)
        flat = lattice.flat_bin_of(locations)  # this block's ONE bin map

        # 1. gather with GLOBAL occupancy (per-block segment-sum, psum
        # over the agent axis). Same raw-vs-shared split as the
        # unsharded fused step: consuming ports see the bin-SHARED view,
        # sense-only ports read the raw gather output.
        raw = ff[:, flat]  # [M, rows]
        if spatial.share_bins:
            occ = lax.psum(
                lattice.occupancy_flat(flat, cs.alive), AGENTS_AXIS
            )
            shared = shared_view(raw, occ, flat, lattice.exchange_scale)
        else:
            shared = raw
        cs = cs._replace(
            agents=apply_gather(plan, cs.agents, cs.alive, raw, shared)
        )

        # 2. biology on this block; stochastic draws fold in the shard id
        shard_key = jax.random.fold_in(cs.key, a_idx)
        cs = colony.step_biology(cs._replace(key=shard_key), timestep)
        cs = cs._replace(key=ss.colony.key)

        # 3. one segment-sum of this block's exchanges into PRE-STEP
        # bins; reduce over agent shards; apply to the strip, one clamp
        if plan.any_exchange:
            from lens_tpu.environment.lattice import masked_exchange_contrib

            payload = exchange_payload(plan, cs.agents, cs.alive.shape[0])
            contrib = masked_exchange_contrib(
                payload, cs.alive, lattice.exchange_scale
            )
            strip = self._apply_exchange_strip(
                strip, ff, flat, contrib, s_idx
            )
            cs = cs._replace(agents=zero_exchanges(plan, cs.agents))
        else:
            # no exchange ports: match the reference block (and the
            # unsharded fused step), which clamps unconditionally
            strip = jnp.maximum(strip, 0.0)

        # 4. per-shard lifecycle + clip, 5. diffusion (shared tail)
        cs = self._block_lifecycle(cs, a_idx)
        strip = self._diffuse_strip(strip, SPACE_AXIS, self.n_space)
        return SpatialState(colony=cs, fields=strip)

    def _block_lifecycle(self, cs, a_idx):
        """Per-shard lifecycle (death, then division), then clip
        locations onto the domain. Death is elementwise — shard-safe
        with no collectives; freed rows rejoin THIS shard's pool."""
        spatial, lattice, colony = (
            self.spatial, self.spatial.lattice, self.spatial.colony
        )
        cs = colony.step_death(cs)
        if colony.division_trigger is not None:
            key, sub = jax.random.split(cs.key)
            sub = jax.random.fold_in(sub, a_idx)
            d_agents, d_alive = colony._divide(
                cs.agents, cs.alive, sub, cs.step
            )
            cs = cs._replace(agents=d_agents, alive=d_alive, key=key)
        from lens_tpu.environment.spatial import clip_to_domain

        return cs._replace(
            agents=clip_to_domain(
                lattice, cs.agents, spatial.location_path
            ),
            step=cs.step + 1,
        )

    def _block_step_reference(
        self, ss: SpatialState, timestep: float
    ) -> SpatialState:
        """The original per-molecule block program (the oracle under
        shard_map, ``coupling="reference"``)."""
        spatial, lattice, colony = self.spatial, self.spatial.lattice, self.spatial.colony
        cs, strip = ss.colony, ss.fields
        a_idx = lax.axis_index(AGENTS_AXIS)
        s_idx = lax.axis_index(SPACE_AXIS)
        h_local = strip.shape[1]

        full_fields = self._assemble_fields(strip, s_idx)  # [M, H, W]
        locations = get_path(cs.agents, spatial.location_path)
        i, j = lattice.bin_of(locations)

        # 1. gather local concentrations, with GLOBAL occupancy (psum over
        # the agent axis) so shared-bin accounting spans shards. Same
        # raw-vs-shared split as the unsharded path (environment.spatial
        # step 1): consuming ports see the bin-SHARED concentration,
        # sense-only ports (exchange=None) see the RAW bin value.
        local_raw = full_fields[:, i, j].T  # [rows, M]
        local_shared = local_raw
        if spatial.share_bins:
            occ = lax.psum(
                lattice.occupancy(locations, cs.alive), AGENTS_AXIS
            )
            local_shared = local_raw / (
                jnp.maximum(occ[i, j], 1.0)[:, None] * lattice.exchange_scale
            )
        agents = cs.agents
        for mol, port in spatial.field_ports.items():
            local = local_raw if port.exchange is None else local_shared
            col = local[:, lattice.index(mol)]
            prev = get_path(agents, port.local)
            agents = set_path(agents, port.local, jnp.where(cs.alive, col, prev))
        cs = cs._replace(agents=agents)

        # 2. biology on this block; stochastic draws fold in the shard id
        shard_key = jax.random.fold_in(cs.key, a_idx)
        cs = colony.step_biology(cs._replace(key=shard_key), timestep)
        cs = cs._replace(key=ss.colony.key)

        # 3. scatter exchanges into PRE-STEP bins; reduce over agent shards
        agents = cs.agents
        rows = cs.alive.shape[0]
        exchange = jnp.stack(
            [
                get_path(agents, spatial.field_ports[mol].exchange)
                if mol in spatial.field_ports
                and spatial.field_ports[mol].exchange is not None
                else jnp.zeros(rows)
                for mol in lattice.molecules
            ],
            axis=1,
        )  # [rows, M]
        contrib = exchange * cs.alive[:, None] * lattice.exchange_scale
        delta = (
            jnp.zeros_like(full_fields).at[:, i, j].add(contrib.T)
        )
        delta = lax.psum(delta, AGENTS_AXIS)
        strip = jnp.maximum(
            strip + lax.dynamic_slice_in_dim(delta, s_idx * h_local, h_local, axis=1),
            0.0,
        )
        for mol, port in spatial.field_ports.items():
            if port.exchange is None:
                continue
            agents = set_path(
                agents, port.exchange,
                jnp.zeros_like(get_path(agents, port.exchange)),
            )
        cs = cs._replace(agents=agents)

        # 4. per-shard lifecycle + clip, 5. diffusion on the strip (halo
        # FTCS, or SPIKE ADI when the lattice opted in — see
        # ShardedRunnerBase._diffuse_strip)
        cs = self._block_lifecycle(cs, a_idx)
        strip = self._diffuse_strip(strip, SPACE_AXIS, self.n_space)
        return SpatialState(colony=cs, fields=strip)

    # -- ShardedRunnerBase hooks --------------------------------------------

    def _lattice(self):
        return self.spatial.lattice

    def _pspecs(self, example: SpatialState):
        return spatial_pspecs(example)

    def _emit_fn(self, carry: SpatialState) -> dict:
        return self.spatial.emit_state(carry)
