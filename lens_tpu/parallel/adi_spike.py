"""Distributed ADI diffusion: SPIKE tridiagonal solves across shards.

The sharded spatial path diffuses with FTCS + ``ppermute`` halo exchange
(parallel.halo) — ~27 collective rounds per window at glucose-like
diffusivities. This module gives the sharded path the same
unconditionally stable backward-Euler ADI step the single-device lattice
has (ops.adi), using the classic substructuring ("SPIKE") decomposition
of the tridiagonal solve along the SHARDED axis:

1.  Each shard factors its LOCAL block ``A_s`` of the global matrix
    ``I - r L`` (interior shards have ordinary ``1+2r`` end rows; only
    the global edge shards carry the Neumann clamp) and solves
    ``u_s = A_s^{-1} d_s`` with the associative-scan Thomas solver.
2.  The true solution is ``x_s = u_s + xL * a_s + xR * b_s`` where
    ``a_s = r A_s^{-1} e_first``, ``b_s = r A_s^{-1} e_last`` (the
    "spikes", precomputed on host in float64) and ``xL``/``xR`` are the
    neighbor shards' boundary values of ``x`` — 2 unknowns per shard.
3.  Writing the consistency equations for those boundary values gives a
    tiny ``2S x 2S`` interface system whose matrix depends only on the
    spikes — its INVERSE is precomputed on host. At runtime the shards
    share their ``u`` boundary rows (one ``psum``-as-all-gather of
    ``[2, M, W]`` per solve), apply the precomputed inverse, and add the
    spike corrections locally.

Net collective traffic per ADI window: ONE boundary exchange for the
sharded axis (the unsharded axis solves locally), versus one ppermute
pair per FTCS substep. The result equals the unsharded ADI step up to
float32 rounding (tested on the virtual mesh), so it inherits its
positivity and exact mass conservation.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from lens_tpu.ops.adi import (
    ThomasFactors,
    dense_tridiag,
    solve_tridiag,
    thomas_factors,
)


class SpikePlan(NamedTuple):
    """Precomputed distributed-ADI step over an ``n_shards``-way axis.

    ``row_factors``: per-shard ThomasFactors, stacked [S, M, n_local] —
    shard ``s`` selects its slice by ``axis_index``. ``spike_a/b``:
    [S, M, n_local] correction vectors. ``interface_inv``: [M, 2S, 2S]
    inverse of the boundary-consistency system (rows/cols ordered
    ``first_0, last_0, first_1, last_1, ...``). ``col_factors``: plain
    local factors for the UNSHARDED axis.
    """

    row_factors: ThomasFactors
    spike_a: jnp.ndarray
    spike_b: jnp.ndarray
    interface_inv: jnp.ndarray
    col_factors: ThomasFactors
    n_shards: int


def spike_plan(alpha: np.ndarray, h: int, w: int, n_shards: int) -> SpikePlan:
    """Build the distributed ADI plan for global fields [M, h, w] with the
    H axis split over ``n_shards`` equal strips.

    ``alpha`` = D*dt/dx^2 per molecule for the WHOLE window.
    """
    if h % n_shards:
        raise ValueError(f"H={h} not divisible by n_shards={n_shards}")
    n_local = h // n_shards
    rs = np.asarray(alpha, np.float64).reshape(-1)
    m = rs.shape[0]
    s2 = 2 * n_shards

    factors = []
    spike_a = np.zeros((n_shards, m, n_local))
    spike_b = np.zeros((n_shards, m, n_local))
    interface = np.zeros((m, s2, s2))
    for s in range(n_shards):
        top, bottom = s == 0, s == n_shards - 1
        factors.append(
            thomas_factors(rs, n_local, clamp_top=top, clamp_bottom=bottom)
        )
        for k in range(m):
            dense = dense_tridiag(rs[k], n_local, top, bottom)
            e0 = np.zeros(n_local)
            e0[0] = rs[k]
            en = np.zeros(n_local)
            en[-1] = rs[k]
            spike_a[s, k] = np.linalg.solve(dense, e0)
            spike_b[s, k] = np.linalg.solve(dense, en)
            # consistency rows for (first_s, last_s):
            #   first_s - a_s[0] last_{s-1} - b_s[0] first_{s+1} = u_s[0]
            interface[k, 2 * s, 2 * s] = 1.0
            interface[k, 2 * s + 1, 2 * s + 1] = 1.0
            if s > 0:
                interface[k, 2 * s, 2 * (s - 1) + 1] = -spike_a[s, k, 0]
                interface[k, 2 * s + 1, 2 * (s - 1) + 1] = -spike_a[s, k, -1]
            if s < n_shards - 1:
                interface[k, 2 * s, 2 * (s + 1)] = -spike_b[s, k, 0]
                interface[k, 2 * s + 1, 2 * (s + 1)] = -spike_b[s, k, -1]

    stacked = ThomasFactors(
        fwd_m=jnp.stack([f.fwd_m for f in factors]),
        fwd_t_scale=jnp.stack([f.fwd_t_scale for f in factors]),
        back_c=jnp.stack([f.back_c for f in factors]),
    )
    return SpikePlan(
        row_factors=stacked,
        spike_a=jnp.asarray(spike_a, jnp.float32),
        spike_b=jnp.asarray(spike_b, jnp.float32),
        interface_inv=jnp.asarray(np.linalg.inv(interface), jnp.float32),
        col_factors=thomas_factors(rs, w),
        n_shards=n_shards,
    )


def solve_spike(plan: SpikePlan, d: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Solve ``(I - r L_global) x = d`` for this shard's strip ``d``
    [M, n_local, W] of the sharded axis. Runs inside shard_map."""
    s = lax.axis_index(axis_name)
    n_shards = plan.n_shards
    fac = ThomasFactors(
        fwd_m=plan.row_factors.fwd_m[s],
        fwd_t_scale=plan.row_factors.fwd_t_scale[s],
        back_c=plan.row_factors.back_c[s],
    )
    u = solve_tridiag(fac, d, axis=1)  # [M, n_local, W]
    if n_shards == 1:
        return u

    m, _, w = u.shape
    ends = jnp.stack([u[:, 0, :], u[:, -1, :]], axis=0)  # [2, M, W]
    # all-gather in psum clothing (matches runner.py's canvas pattern, and
    # keeps the result provably shard-invariant for the rep checker)
    canvas = lax.dynamic_update_slice_in_dim(
        jnp.zeros((2 * n_shards,) + ends.shape[1:], ends.dtype),
        ends, 2 * s, axis=0,
    )
    all_ends = lax.psum(canvas, axis_name)  # [2S, M, W], (first_s, last_s)

    # interface solve: y = inv @ u_ends, per molecule
    y = jnp.einsum("mab,bmw->amw", plan.interface_inv, all_ends)  # [2S, M, W]

    # neighbor boundary values of the TRUE solution
    xL = lax.dynamic_index_in_dim(  # last_{s-1}
        y, jnp.clip(2 * s - 1, 0, 2 * n_shards - 1), axis=0, keepdims=False
    )
    xR = lax.dynamic_index_in_dim(  # first_{s+1}
        y, jnp.clip(2 * s + 2, 0, 2 * n_shards - 1), axis=0, keepdims=False
    )
    xL = jnp.where(s > 0, xL, 0.0)
    xR = jnp.where(s < n_shards - 1, xR, 0.0)

    a_vec = plan.spike_a[s]  # [M, n_local]
    b_vec = plan.spike_b[s]
    return (
        u
        + a_vec[:, :, None] * xL[:, None, :]
        + b_vec[:, :, None] * xR[:, None, :]
    )


def diffuse_adi_sharded(
    strip: jnp.ndarray, plan: SpikePlan, axis_name: str
) -> jnp.ndarray:
    """One backward-Euler ADI window on a sharded field strip
    [M, n_local, W]: SPIKE solve along the sharded axis, local solve
    along the other. Runs inside shard_map."""
    u = solve_spike(plan, strip, axis_name)
    return solve_tridiag(plan.col_factors, u, axis=2)
