"""Mesh construction and sharding specs for colony/spatial state.

One place defines how simulation state maps onto devices, so the jit
(auto-partitioned) path, the shard_map (explicit-collective) path, and
the driver's multichip dry run all agree. Replaces the reference's
"which host runs which agent process" bookkeeping in the shepherd
(reconstructed: ``lens/actor/shepherd.py``, SURVEY.md §2).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AGENTS_AXIS = "agents"
SPACE_AXIS = "space"


def resolve_mesh_devices(
    n_agents: Optional[int],
    n_space: int,
    devices: Optional[Sequence],
) -> Tuple[list, int]:
    """Shared defaulting/validation for mesh construction: returns the
    (truncated) device list and the resolved agent-axis size."""
    devices = list(devices if devices is not None else jax.devices())
    if n_agents is None:
        if len(devices) % n_space:
            raise ValueError(
                f"{len(devices)} devices not divisible by n_space={n_space}"
            )
        n_agents = len(devices) // n_space
    n = n_agents * n_space
    if n > len(devices):
        raise ValueError(f"mesh wants {n} devices, have {len(devices)}")
    return devices[:n], n_agents


def make_mesh(
    n_agents: Optional[int] = None,
    n_space: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A 2D (agents x space) mesh over ``devices`` (default: all).

    ``n_agents`` defaults to ``len(devices) // n_space``. Either axis may
    be 1 (pure agent-DP or pure spatial decomposition).
    """
    devices, n_agents = resolve_mesh_devices(n_agents, n_space, devices)
    return Mesh(
        np.asarray(devices).reshape(n_agents, n_space),
        axis_names=(AGENTS_AXIS, SPACE_AXIS),
    )


def serve_devices(mesh=None) -> list:
    """Resolve a serving-mesh spec into the ordered per-shard device
    list ``SimServer`` places bucket lane pools on (one ``LanePool``
    per entry — the serving failure domain is one device).

    ``None`` -> ``[None]``: a single uncommitted pool on the default
    device, the pre-mesh behavior bit for bit. ``int n`` -> the first
    ``n`` of ``jax.devices()``. A :class:`jax.sharding.Mesh` -> its
    devices in flat order (the serve layer packs independent lanes, so
    only the device LIST matters — axis structure is the SPMD
    runners' concern). Any other sequence -> taken as an explicit
    device list.
    """
    if mesh is None:
        return [None]
    if isinstance(mesh, Mesh):
        return list(np.asarray(mesh.devices).flat)
    if isinstance(mesh, (int, np.integer)):
        n = int(mesh)
        if n < 1:
            raise ValueError(f"mesh={n} must be >= 1 devices")
        devices = jax.devices()
        if n > len(devices):
            raise ValueError(
                f"mesh={n} devices requested but only {len(devices)} "
                f"are attached (on CPU, simulate more with "
                f"XLA_FLAGS=--xla_force_host_platform_device_count=N)"
            )
        return devices[:n]
    devices = list(mesh)
    if not devices:
        raise ValueError("mesh device list is empty")
    return devices


def colony_pspecs(colony_state) -> "jax.tree_util.PyTreeDef":
    """PartitionSpecs for a ColonyState: agent leaves split on the agent
    axis, PRNG key and step counter replicated."""
    agents = jax.tree.map(
        lambda leaf: P(AGENTS_AXIS, *([None] * (leaf.ndim - 1))),
        colony_state.agents,
    )
    return type(colony_state)(
        agents=agents, alive=P(AGENTS_AXIS), key=P(), step=P()
    )


def spatial_pspecs(spatial_state) -> "jax.tree_util.PyTreeDef":
    """PartitionSpecs for a SpatialState: colony as above; fields [M, H, W]
    split along H on the space axis (replicated across the agent axis)."""
    return type(spatial_state)(
        colony=colony_pspecs(spatial_state.colony),
        fields=P(None, SPACE_AXIS, None),
    )


def multispecies_pspecs(ms_state) -> "jax.tree_util.PyTreeDef":
    """PartitionSpecs for a MultiSpeciesState: every species' ColonyState
    split on the agent axis (each species' rows are their own block per
    device — capacities need not match across species), shared fields
    [M, H, W] split along H on the space axis."""
    return type(ms_state)(
        species={
            name: colony_pspecs(cs) for name, cs in ms_state.species.items()
        },
        fields=P(None, SPACE_AXIS, None),
    )


def mesh_shardings(mesh: Mesh, pspecs):
    """Turn a pytree of PartitionSpecs into NamedShardings on ``mesh``."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        pspecs,
        is_leaf=lambda x: isinstance(x, P),
    )


def stripe_colony_rows(colony_state, n_blocks: int):
    """Permute a ColonyState's rows so initially-alive cells spread
    EVENLY across the ``n_blocks`` agent-axis shards.

    ``initial_state`` marks rows ``[0, n_alive)`` alive; distributed
    contiguously, they pile into the first shards — shard 0's division
    pool exhausts (``division_backlog`` > 0) while later shards sit
    empty. Before any dynamics all rows are exchangeable, so a pure
    permutation is biology-neutral; after it, old row ``i`` sits at
    block ``i % n_blocks``, slot ``i // n_blocks`` — founders and free
    rows alike are dealt round-robin across shards.
    """
    cap = colony_state.alive.shape[0]
    if cap % n_blocks:
        raise ValueError(f"capacity {cap} not divisible by {n_blocks} blocks")
    block = cap // n_blocks
    p = jnp.arange(cap)
    src = (p % block) * n_blocks + p // block
    take = lambda leaf: leaf[src]
    return colony_state._replace(
        agents=jax.tree.map(take, colony_state.agents),
        alive=take(colony_state.alive),
    )


def interleave_expanded_rows(colony_state, old_cap: int, n_blocks: int):
    """Deal a capacity expansion's fresh rows evenly across agent shards.

    ``Colony.expanded`` appends its new (dead, template) rows at the END
    of the row axis; split contiguously over ``n_blocks`` shards, that
    layout would dump every fresh row into the tail shards and re-create
    the saturation skew striping exists to prevent. Fresh rows are
    exchangeable, so a pure permutation fixes it: new block ``b`` is
    ``[old block b | its share of fresh rows]`` — every shard keeps its
    old rows AND gains the same number of free slots.

    CONTRACT NOTE: this permutation renumbers live rows, so emitted
    trajectories from before and after a sharded expansion do NOT align
    row-for-row (the stacked series pads at the end while agents moved
    elsewhere). Row index was never a cross-time identity in a dividing
    colony anyway — daughters recycle dead rows every step; the stable
    identity is ``lineage.cell_id``, which rides the permutation and is
    what the analysis layer's lineage tools key on.
    """
    cap = colony_state.alive.shape[0]
    if old_cap % n_blocks or cap % n_blocks:
        raise ValueError(
            f"capacities {old_cap}->{cap} not divisible by {n_blocks} blocks"
        )
    b_old = old_cap // n_blocks
    b_fresh = (cap - old_cap) // n_blocks
    src = jnp.concatenate(
        [
            jnp.concatenate(
                [
                    jnp.arange(b * b_old, (b + 1) * b_old),
                    old_cap + jnp.arange(b * b_fresh, (b + 1) * b_fresh),
                ]
            )
            for b in range(n_blocks)
        ]
    )
    take = lambda leaf: leaf[src]
    return colony_state._replace(
        agents=jax.tree.map(take, colony_state.agents),
        alive=take(colony_state.alive),
    )


def rebalance_colony_rows(colony_state, n_blocks: int):
    """Re-deal ALL rows round-robin by alive-rank so every agent shard
    ends up with an equal (±1) share of alive AND free rows.

    Division pools are shard-local by design (free rows never cross a
    shard boundary), which a lineage with an inherited fast phenotype can
    exploit into real divergence: its daughters recycle rows in the
    parent's shard until that pool saturates, suppressing divisions the
    unsharded colony would perform (measured: a 3x-rate founder lineage
    on one of 8 shards starved at 16/128 rows and the population ran 52%
    behind unsharded — tests/test_experiment.py::
    TestHeterogeneousDivergence). This permutation is the
    cure: stable-sort rows alive-first (order preserved within each
    class), deal them round-robin across blocks. Like striping and
    expansion interleaving it is biology-neutral — row identity is
    ``lineage.cell_id``, which rides the permutation; row INDEX was never
    a cross-time identity in a dividing colony.

    Cross-shard by nature (rows move between devices), so run it rarely —
    the Experiment applies it at segment boundaries, and only when the
    backlog/free-row telemetry says a shard is starved while global
    capacity remains (``Experiment._maybe_rebalance``).
    """
    cap = colony_state.alive.shape[0]
    if cap % n_blocks:
        raise ValueError(f"capacity {cap} not divisible by {n_blocks} blocks")
    block = cap // n_blocks
    order = jnp.argsort(~colony_state.alive, stable=True)
    p = jnp.arange(cap)
    src = order[(p % block) * n_blocks + p // block]
    take = lambda leaf: leaf[src]
    return colony_state._replace(
        agents=jax.tree.map(take, colony_state.agents),
        alive=take(colony_state.alive),
    )


def expand_colony_rows_on_mesh(colony_state, grown_colony, old_cap: int,
                               mesh: Mesh):
    """Capacity expansion of a mesh-sharded ColonyState, entirely on
    device: every agent shard pads ITS OWN block with its share of fresh
    template rows — no host gather, no collectives, no cross-shard data
    movement. This is the multi-host-safe replacement for the
    ``device_get -> Colony.expanded -> interleave_expanded_rows ->
    device_put`` sequence, and is bitwise-equal to it (tested): the
    composition of end-appended padding with the interleave permutation
    IS the shard-local layout ``[old block b | block b's fresh rows]``.

    ``grown_colony`` comes from :meth:`Colony.expanded_meta` (it carries
    the new capacity and the lineage id watermark); fresh rows are schema
    defaults except ``lineage.row_id``/``cell_id``, which continue the
    global arange exactly as ``Colony.expanded`` pads them
    (``template[old_cap:]``), so ids stay globally unique across shards.

    Returns the expanded ColonyState, sharded on ``mesh`` per
    :func:`colony_pspecs`.
    """
    from lens_tpu.colony.colony import Colony

    n_blocks = mesh.shape[AGENTS_AXIS]
    new_cap = grown_colony.capacity
    if old_cap % n_blocks or new_cap % n_blocks:
        raise ValueError(
            f"capacities {old_cap}->{new_cap} not divisible by "
            f"{n_blocks} agent shards"
        )
    b_fresh = (new_cap - old_cap) // n_blocks
    # A shard-block-sized template: schema defaults are capacity-
    # independent; the arange-valued lineage leaves are shifted per
    # shard inside the block program below.
    tmpl = Colony(
        grown_colony.compartment,
        b_fresh,
        division_trigger=grown_colony.division_trigger,
        death_trigger=grown_colony.death_trigger,
    ).initial_state(0).agents

    in_specs = colony_pspecs(colony_state)
    out_specs = in_specs

    def pad_block(cs_blk):
        fresh = tmpl
        if "lineage" in fresh:
            shift = jnp.int32(old_cap) + lax.axis_index(
                AGENTS_AXIS
            ).astype(jnp.int32) * jnp.int32(b_fresh)
            fresh = dict(
                fresh,
                lineage=dict(
                    fresh["lineage"],
                    row_id=fresh["lineage"]["row_id"] + shift,
                    cell_id=fresh["lineage"]["cell_id"] + shift,
                ),
            )
        agents = jax.tree.map(
            lambda old, t: jnp.concatenate([old, t.astype(old.dtype)], axis=0),
            cs_blk.agents,
            fresh,
        )
        alive = jnp.concatenate(
            [cs_blk.alive, jnp.zeros(b_fresh, bool)]
        )
        return cs_blk._replace(agents=agents, alive=alive)

    from lens_tpu.utils.platform import shard_map_fn

    grow = jax.jit(
        shard_map_fn()(
            pad_block, mesh=mesh, in_specs=(in_specs,), out_specs=out_specs
        )
    )
    return grow(colony_state)


def validate_divisible(capacity: int, field_h: int, mesh: Mesh) -> None:
    n_a = mesh.shape[AGENTS_AXIS]
    n_s = mesh.shape[SPACE_AXIS]
    if capacity % n_a:
        raise ValueError(f"capacity {capacity} not divisible by agents axis {n_a}")
    if field_h % n_s:
        raise ValueError(f"field height {field_h} not divisible by space axis {n_s}")
