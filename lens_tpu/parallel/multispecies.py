"""ShardedMultiSpeciesColony: the mixed-species flagship on a device mesh.

The single-species SPMD step (``parallel.runner.ShardedSpatialColony``)
shards one colony's agent axis; the north-star scenario (BASELINE.json
config 4 — a 100k-cell mixed colony) is a ``MultiSpeciesColony``: N
species with DISTINCT process sets coupled through ONE lattice
(``environment.multispecies``). This module gives that colony the same
explicit-collective layout (SURVEY.md §2 parallelism table — agent-axis
sharding is mandated for *all* colony forms):

- every species' agent axis is split over the ``agents`` mesh axis —
  each device holds one block of rows of EVERY species, so each species'
  biology stays one clean per-block ``vmap`` (no schema union, no masked
  FLOPs — the same property the unsharded design was chosen for);
- the shared fields strip is split over the ``space`` axis exactly as in
  the single-species runner (``all_gather``-style psum assembly,
  ``ppermute`` diffusion halos);
- the cross-species couplings are the two reductions the unsharded step
  does in HBM: **combined occupancy** (sum over species, then ``psum``
  over the agent axis) and the **combined exchange delta** (one
  scatter-add canvas summed over species and shards, one ``>= 0`` clamp)
  — so shared-bin mass conservation spans species AND shards.

Division stays per species per shard (each species-block has its own
free-row pool), mirroring the single-species runner's design decision;
the ``division_backlog`` emit makes per-shard saturation observable.

PRNG discipline matches the runner: each species' stored key advances
identically on every shard; stochastic draws fold in the shard's
``axis_index`` so shards sample independent streams.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

from lens_tpu.colony.colony import ColonyState
from lens_tpu.environment.multispecies import (
    MultiSpeciesColony,
    MultiSpeciesState,
)
from lens_tpu.environment.spatial import (
    apply_gather,
    exchange_payload,
    shared_view,
    zero_exchanges,
)
from lens_tpu.parallel.base import ShardedRunnerBase
from lens_tpu.parallel.mesh import (
    AGENTS_AXIS,
    SPACE_AXIS,
    multispecies_pspecs,
    validate_divisible,
)
from lens_tpu.utils.dicts import get_path, set_path


class ShardedMultiSpeciesColony(ShardedRunnerBase):
    """Wraps a MultiSpeciesColony with a mesh-sharded step/run.

    The wrapped ``multi`` provides all wiring (per-species field ports,
    location paths, share_bins) and the per-block biology; this class
    owns only the collectives. Deterministic composites produce
    trajectories equal to the unsharded path (tested); stochastic
    composites draw per-shard streams, so trajectories differ from
    unsharded by PRNG layout only.
    """

    def __init__(self, multi: MultiSpeciesColony, mesh: Mesh):
        for name, sp in multi.species.items():
            try:
                validate_divisible(
                    sp.colony.capacity, multi.lattice.shape[0], mesh
                )
            except ValueError as e:
                raise ValueError(f"species {name!r}: {e}") from None
        super().__init__(mesh)
        self.multi = multi
        self.n_space = mesh.shape[SPACE_AXIS]

    # -- construction --------------------------------------------------------

    def initial_state(
        self, n_alive, key, stripe: bool = True, **kwargs
    ) -> MultiSpeciesState:
        """Build on host, then place per the mesh layout (multi-host safe
        via :func:`parallel.distributed.distribute`). ``stripe`` deals
        each species' alive rows round-robin across agent shards (see
        :meth:`ShardedSpatialColony.initial_state`)."""
        from lens_tpu.parallel.distributed import distribute
        from lens_tpu.parallel.mesh import stripe_colony_rows

        ms = self.multi.initial_state(n_alive, key, **kwargs)
        if stripe:
            n_blocks = self.mesh.shape[AGENTS_AXIS]
            ms = ms._replace(
                species={
                    name: stripe_colony_rows(cs, n_blocks)
                    for name, cs in ms.species.items()
                }
            )
        return distribute(ms, self.mesh, multispecies_pspecs(ms))

    # -- the SPMD step -------------------------------------------------------

    def _block_step(
        self, ms: MultiSpeciesState, timestep: float
    ) -> MultiSpeciesState:
        """Per-device block program (runs inside shard_map). Mirrors
        ``MultiSpeciesColony.step`` stage for stage; every cross-device
        movement is an explicit collective. Honors the wrapped multi's
        ``coupling`` knob (fused CouplingPlan one-pass vs the original
        per-molecule reference oracle)."""
        if self.multi.coupling == "fused":
            return self._block_step_fused(ms, timestep)
        return self._block_step_reference(ms, timestep)

    def _block_lifecycle(self, stepped, a_idx):
        """Per-shard lifecycle per species (death, then division), then
        clip onto the domain — shared by both coupling paths."""
        from lens_tpu.environment.spatial import clip_to_domain

        multi, lattice = self.multi, self.multi.lattice
        for name, sp in multi.species.items():
            cs = sp.colony.step_death(stepped[name])
            if sp.colony.division_trigger is not None:
                key, sub = jax.random.split(cs.key)
                sub = jax.random.fold_in(sub, a_idx)
                d_agents, d_alive = sp.colony._divide(
                    cs.agents, cs.alive, sub, cs.step
                )
                cs = cs._replace(agents=d_agents, alive=d_alive, key=key)
            stepped[name] = cs._replace(
                agents=clip_to_domain(lattice, cs.agents, sp.location_path),
                step=cs.step + 1,
            )
        return stepped

    def _block_step_fused(
        self, ms: MultiSpeciesState, timestep: float
    ) -> MultiSpeciesState:
        """The fused multi-species coupling on a device mesh: ONE flat
        bin map over this block's concatenated all-species rows, the
        combined occupancy and exchange delta as plan-driven
        segment-sums psum'd over the agent axis — so shared-bin mass
        conservation spans species AND shards, at one index derivation
        per step."""
        multi, lattice = self.multi, self.multi.lattice
        strip = ms.fields
        a_idx = lax.axis_index(AGENTS_AXIS)
        s_idx = lax.axis_index(SPACE_AXIS)
        full_fields = self._assemble_fields(strip, s_idx)  # [M, H, W]
        n_mols = len(lattice.molecules)
        ff = full_fields.reshape(n_mols, lattice.n_bins)

        row_slices = multi._row_slices(ms)
        all_locs, all_alive = multi._concat_rows(ms)
        flat = lattice.flat_bin_of(all_locs)  # the block's ONE bin map

        # 1. ONE gather for all species; combined GLOBAL occupancy
        # (per-block segment-sum over every species' rows, psum over
        # agent shards). Sense-only ports read the raw gather output.
        raw = ff[:, flat]  # [M, rows_all]
        if multi.share_bins:
            occ = lax.psum(
                lattice.occupancy_flat(flat, all_alive), AGENTS_AXIS
            )
            shared = shared_view(raw, occ, flat, lattice.exchange_scale)
        else:
            shared = raw
        stepped: Dict[str, ColonyState] = {}
        for name, sp in multi.species.items():
            cs = ms.species[name]
            stepped[name] = cs._replace(
                agents=apply_gather(
                    sp.plan, cs.agents, cs.alive,
                    raw[:, row_slices[name]], shared[:, row_slices[name]],
                )
            )

        # 2. biology per species; stochastic draws fold in the shard id
        for name, sp in multi.species.items():
            cs = stepped[name]
            shard_key = jax.random.fold_in(cs.key, a_idx)
            cs = sp.colony.step_biology(
                cs._replace(key=shard_key), timestep
            )
            stepped[name] = cs._replace(key=stepped[name].key)

        # 3. ONE segment-sum of all species' exchanges into the PRE-STEP
        # bins, psum over agent shards, ONE clamp
        payloads = []
        for name, sp in multi.species.items():
            cs = stepped[name]
            payloads.append(
                exchange_payload(sp.plan, cs.agents, cs.alive.shape[0])
            )  # [M, rows]
            stepped[name] = cs._replace(
                agents=zero_exchanges(sp.plan, cs.agents)
            )
        from lens_tpu.environment.lattice import masked_exchange_contrib

        contrib = masked_exchange_contrib(
            jnp.concatenate(payloads, axis=1), all_alive,
            lattice.exchange_scale,
        )
        strip = self._apply_exchange_strip(strip, ff, flat, contrib, s_idx)

        # 4. per-shard lifecycle per species + clip, 5. diffusion
        stepped = self._block_lifecycle(stepped, a_idx)
        strip = self._diffuse_strip(strip, SPACE_AXIS, self.n_space)
        return MultiSpeciesState(species=stepped, fields=strip)

    def _block_step_reference(
        self, ms: MultiSpeciesState, timestep: float
    ) -> MultiSpeciesState:
        """The original per-molecule block program (the oracle under
        shard_map, ``coupling="reference"``)."""
        multi, lattice = self.multi, self.multi.lattice
        strip = ms.fields
        a_idx = lax.axis_index(AGENTS_AXIS)
        s_idx = lax.axis_index(SPACE_AXIS)
        h_local = strip.shape[1]
        full_fields = self._assemble_fields(strip, s_idx)  # [M, H, W]

        # This block's rows of EVERY species, concatenated — the SAME
        # row-slice/concat methods the unsharded step uses (shape-
        # polymorphic over the block row count), so the two paths cannot
        # desynchronize.
        row_slices = multi._row_slices(ms)
        all_locs, all_alive = multi._concat_rows(ms)
        bi, bj = lattice.bin_of(all_locs)

        # Cross-species combined occupancy: this block's live cells of
        # every species per bin, psum over agent shards -> the same
        # global [H, W] occupancy the unsharded step computes in HBM.
        occ = None
        if multi.share_bins:
            occ = lax.psum(
                lattice.occupancy(all_locs, all_alive), AGENTS_AXIS
            )

        # 1. ONE gather for all species from the assembled field
        # (consuming ports see the ALL-species shared concentration;
        # sense-only ports the raw bin value — same split as
        # environment.spatial step 1), split by static row slices
        local_raw_all = full_fields[:, bi, bj].T  # [rows_all, M]
        local_shared_all = local_raw_all
        if multi.share_bins:
            local_shared_all = local_raw_all / (
                jnp.maximum(occ[bi, bj], 1.0)[:, None]
                * lattice.exchange_scale
            )
        stepped: Dict[str, ColonyState] = {}
        for name, sp in multi.species.items():
            cs = ms.species[name]
            agents = cs.agents
            for mol, port in sp.field_ports.items():
                local = (
                    local_raw_all if port.exchange is None
                    else local_shared_all
                )
                col = local[row_slices[name], lattice.index(mol)]
                prev = get_path(agents, port.local)
                agents = set_path(
                    agents, port.local, jnp.where(cs.alive, col, prev)
                )
            stepped[name] = cs._replace(agents=agents)

        # 2. biology per species — one vmap per process set per block;
        # stochastic draws fold in the shard id, stored key unchanged
        for name, sp in multi.species.items():
            cs = stepped[name]
            shard_key = jax.random.fold_in(cs.key, a_idx)
            cs = sp.colony.step_biology(
                cs._replace(key=shard_key), timestep
            )
            stepped[name] = cs._replace(key=stepped[name].key)

        # 3. ONE scatter of all species' exchanges into the PRE-STEP
        # bins: combined full-canvas delta, psum over agent shards, ONE
        # clamp
        exchanges = []
        for name, sp in multi.species.items():
            cs = stepped[name]
            agents = cs.agents
            n_rows = cs.alive.shape[0]
            exchanges.append(
                jnp.stack(
                    [
                        get_path(agents, sp.field_ports[mol].exchange)
                        if mol in sp.field_ports
                        and sp.field_ports[mol].exchange is not None
                        else jnp.zeros(n_rows)
                        for mol in lattice.molecules
                    ],
                    axis=1,
                )
            )  # [rows, M]
            for mol, port in sp.field_ports.items():
                if port.exchange is None:
                    continue
                agents = set_path(
                    agents, port.exchange,
                    jnp.zeros_like(get_path(agents, port.exchange)),
                )
            stepped[name] = cs._replace(agents=agents)
        contrib = (
            jnp.concatenate(exchanges)
            * all_alive[:, None]
            * lattice.exchange_scale
        )
        delta = jnp.zeros_like(full_fields).at[:, bi, bj].add(contrib.T)
        delta = lax.psum(delta, AGENTS_AXIS)
        strip = jnp.maximum(
            strip
            + lax.dynamic_slice_in_dim(delta, s_idx * h_local, h_local, axis=1),
            0.0,
        )

        # 4. per-shard lifecycle per species + clip, 5. diffusion on the
        # strip, once (halo FTCS, or SPIKE ADI when the lattice opted in
        # — see ShardedRunnerBase._diffuse_strip)
        stepped = self._block_lifecycle(stepped, a_idx)
        strip = self._diffuse_strip(strip, SPACE_AXIS, self.n_space)
        return MultiSpeciesState(species=stepped, fields=strip)

    # -- ShardedRunnerBase hooks --------------------------------------------

    def _lattice(self):
        return self.multi.lattice

    def _pspecs(self, example: MultiSpeciesState):
        return multispecies_pspecs(example)

    def _emit_fn(self, carry: MultiSpeciesState) -> dict:
        return self.multi.emit_state(carry)
