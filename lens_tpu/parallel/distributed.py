"""Multi-host runtime: jax.distributed bring-up + cross-host mesh + IO guards.

The reference runs multi-host colonies by pointing every host's shepherd
at the same Kafka broker — coordination is the broker's problem
(reconstructed: ``lens/actor/shepherd.py`` + boot args, SURVEY.md §2
"distributed communication backend"). The rebuild has no broker: hosts
join one JAX distributed runtime (a coordinator handshakes PJRT over
DCN), every host runs the SAME SPMD program, and cross-host movement is
the XLA collectives the program already contains — ``psum``/``ppermute``
over mesh axes that now span slices. This module is the small explicit
control plane SURVEY.md §2 requires:

- :func:`initialize` — idempotent ``jax.distributed.initialize`` wrapper
  (env-driven defaults, no-op single-host, safe under repeat calls);
- :func:`global_mesh` — the 2D (agents x space) colony mesh over ALL
  hosts' devices, ICI-contiguous via ``mesh_utils`` so the agent axis
  (heavy psum traffic) stays on-slice and only halo/occupancy traffic
  crosses DCN;
- :func:`distribute` — host-local state -> global sharded arrays
  (every host constructs the same full-size pytree; each keeps only its
  addressable shards);
- :func:`is_coordinator` / :func:`coordinator_only` — IO discipline:
  emit logs, checkpoints directory creation, and progress prints happen
  once, on process 0, not once per host.

Single-process (tests, laptops, the bench chip) everything degrades to a
no-op: ``initialize()`` returns False, ``global_mesh`` equals
``make_mesh``, ``coordinator_only`` runs the function.
"""

from __future__ import annotations

import functools
import os
from typing import Callable, Optional, Sequence, TypeVar

import jax
import numpy as np
from jax.sharding import Mesh

from lens_tpu.parallel.mesh import AGENTS_AXIS, SPACE_AXIS, mesh_shardings

F = TypeVar("F", bound=Callable)

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host runtime. Returns True if distributed is active.

    Multi-host is OPT-IN: the handshake runs only when a coordinator
    address is given (argument or ``JAX_COORDINATOR_ADDRESS``) or
    ``LENS_TPU_DISTRIBUTED=1`` asks for jax's cluster auto-detection
    (TPU pods with a cluster manager need no explicit address). Anything
    else — laptops, CI, the tunneled bench chip (which exports pod-like
    env vars such as ``TPU_WORKER_HOSTNAMES``) — is a single-host no-op.
    Idempotent: repeat calls (e.g. experiment retries) do not
    re-handshake. Returns True iff more than one process is attached.
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    auto = os.environ.get("LENS_TPU_DISTRIBUTED") == "1"
    if coordinator_address is None and not auto:
        return False
    env_n = os.environ.get("JAX_NUM_PROCESSES")
    env_id = os.environ.get("JAX_PROCESS_ID")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=(
            int(num_processes) if num_processes is not None
            else int(env_n) if env_n else None
        ),
        process_id=(
            int(process_id) if process_id is not None
            else int(env_id) if env_id else None
        ),
    )
    _initialized = True
    return jax.process_count() > 1


def cluster_identity(
    host_id: Optional[int] = None,
    n_hosts: Optional[int] = None,
) -> tuple:
    """(host index, host count) for cluster serving bring-up
    (docs/serving.md, "Cluster serving").

    Explicit arguments win — the simulated-hosts mode (the cluster
    router spawning localhost workers) passes both. With neither
    given, the identity comes from the jax.distributed runtime when
    :func:`initialize` attached more than one process (one serve
    worker per host, numbered by ``jax.process_index`` — the
    Podracer/Sebulba shape: per-host actors behind a central work
    source), and degrades to ``(0, 1)`` single-host otherwise.
    Mixing one explicit value with one default is refused — a worker
    that knows its index but not the fleet size (or vice versa)
    indicates a broken launcher."""
    if (host_id is None) != (n_hosts is None):
        raise ValueError(
            f"cluster_identity needs both host_id and n_hosts or "
            f"neither, got host_id={host_id} n_hosts={n_hosts}"
        )
    if host_id is not None:
        host_id, n_hosts = int(host_id), int(n_hosts)
        if not 0 <= host_id < n_hosts:
            raise ValueError(
                f"host_id={host_id} out of range for "
                f"n_hosts={n_hosts}"
            )
        return host_id, n_hosts
    return jax.process_index(), jax.process_count()


def is_coordinator() -> bool:
    """True on the process that owns IO (process 0; single-host: always)."""
    return jax.process_index() == 0


def coordinator_only(fn: F) -> F:
    """Run ``fn`` only on process 0; other hosts get None.

    Host-side IO (emit drain, checkpoint-dir creation, progress prints)
    must not happen once per host. Device-side collectives must NOT be
    guarded this way — every host must trace identical programs.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if is_coordinator():
            return fn(*args, **kwargs)
        return None

    return wrapper


def global_mesh(
    n_agents: Optional[int] = None,
    n_space: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """The colony mesh over every host's devices, ICI-contiguous.

    Like :func:`lens_tpu.parallel.mesh.make_mesh` but (a) defaults to the
    GLOBAL device list and (b) lays the (agents, space) grid out with
    ``mesh_utils.create_device_mesh``, which orders devices so the inner
    axis rides ICI neighbors — keeping the agent-axis ``psum`` (the heavy
    per-step reduction) inside a slice wherever the shape allows, with
    only the thin halo/occupancy traffic crossing DCN.
    """
    from jax.experimental import mesh_utils

    from lens_tpu.parallel.mesh import resolve_mesh_devices

    devices, n_agents = resolve_mesh_devices(n_agents, n_space, devices)
    try:
        grid = mesh_utils.create_device_mesh(
            (n_agents, n_space), devices=devices
        )
    except (ValueError, AssertionError):
        # Topologies mesh_utils cannot factor (odd CPU counts, forced
        # host platforms): plain row-major order is still correct.
        grid = np.asarray(devices).reshape(n_agents, n_space)
    return Mesh(grid, axis_names=(AGENTS_AXIS, SPACE_AXIS))


def place_like(leaf, sharding):
    """One host-local array -> a device array with ``sharding``.

    Multi-host safe: ``jax.device_put`` only works single-process (a
    NamedSharding spanning non-addressable devices rejects it); on a
    multi-host mesh each process materializes just its addressable
    shards via ``make_array_from_callback``.
    """
    if jax.process_count() == 1:
        return jax.device_put(leaf, sharding)
    return jax.make_array_from_callback(
        np.shape(leaf), sharding, lambda idx: np.asarray(leaf)[idx]
    )


def distribute(state, mesh: Mesh, pspecs):
    """Host-local full-size state -> globally sharded device arrays.

    Every host calls this with an IDENTICALLY constructed ``state`` (same
    seed, same config — cheap: colony init is a few array fills). Each
    host then keeps only its addressable shards, so no host ever needs
    another's memory and no cross-host scatter happens at startup.
    """
    shardings = mesh_shardings(mesh, pspecs)
    return jax.tree.map(place_like, state, shardings)
