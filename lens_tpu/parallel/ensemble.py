"""Replicate-parallel execution: ensembles sharded over the device mesh.

Replicates are fully independent (colony.Ensemble: separate PRNG
streams, no shared fields), which makes the replicate axis the
cheapest perfectly-scaling parallel dimension the framework has: no
collectives, no halo exchange, no cross-shard division pools — the
compiler partitions the batched program over the mesh and the
interconnect carries nothing at all. Where the reference would place N
replicate experiments as N separate process clusters through its broker
tier (reconstructed: SURVEY.md §3.3 shepherd placement), here placement
is a sharding annotation on the leading state axis.

Because there is genuinely no cross-replicate communication, this runner
deliberately uses jit + ``NamedSharding`` (XLA's batch partitioner)
rather than ``shard_map``: there is no collective to make explicit, and
jit keeps the whole Ensemble surface (``run``, ``run_timeline``) working
unchanged on sharded inputs.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lens_tpu.colony.ensemble import Ensemble
from lens_tpu.parallel.base import cached_jit
from lens_tpu.parallel.mesh import AGENTS_AXIS, make_mesh


class ShardedEnsemble:
    """An :class:`~lens_tpu.colony.ensemble.Ensemble` whose replicate
    axis is split across the devices of a mesh axis.

    ``mesh`` defaults to all local devices on one ``agents`` axis (the
    replicate axis IS agent-level data parallelism, one level up).
    ``n_replicates`` must divide evenly by the axis size.
    """

    def __init__(
        self,
        ensemble: Ensemble,
        mesh: Optional[Mesh] = None,
        axis: str = AGENTS_AXIS,
    ):
        if mesh is None:
            mesh = make_mesh(n_space=1)
        if axis not in mesh.axis_names:
            raise ValueError(
                f"mesh has axes {mesh.axis_names}, no {axis!r}"
            )
        n_dev = mesh.shape[axis]
        if ensemble.n_replicates % n_dev:
            raise ValueError(
                f"n_replicates={ensemble.n_replicates} does not divide "
                f"across {n_dev} devices on the {axis!r} mesh axis"
            )
        self.ensemble = ensemble
        self.mesh = mesh
        self.axis = axis
        self._run_cache: dict = {}

    # -- sharding ------------------------------------------------------------

    def _leaf_sharding(self, leaf) -> NamedSharding:
        """Every ensemble state leaf carries the replicate axis FIRST
        (vmapped construction), so one rule shards the whole tree."""
        return NamedSharding(
            self.mesh, P(self.axis, *([None] * (leaf.ndim - 1)))
        )

    def shard(self, states):
        """Place an ensemble state pytree onto the mesh, replicate axis
        split across ``axis`` (multi-host safe: each process materializes
        only its addressable shards)."""
        from lens_tpu.parallel.distributed import place_like

        return jax.tree.map(
            lambda leaf: place_like(leaf, self._leaf_sharding(leaf)),
            states,
        )

    # -- Ensemble surface ----------------------------------------------------

    def initial_state(self, *args, key: jax.Array, **kwargs):
        """Build the stacked initial states and shard them."""
        return self.shard(
            self.ensemble.initial_state(*args, key=key, **kwargs)
        )

    def run(
        self, states, total_time: float, timestep: float, emit_every: int = 1
    ) -> Tuple[Any, dict]:
        """The plain Ensemble program on sharded inputs: XLA's batch
        partitioner splits every per-replicate computation across the
        mesh; outputs stay sharded (trajectory leaves [T, R, ...] carry
        the replicate sharding on axis 1)."""
        fn = cached_jit(
            self._run_cache,
            (float(total_time), float(timestep), int(emit_every)),
            lambda: jax.jit(
                lambda s: self.ensemble.run(
                    s, total_time, timestep, emit_every
                )
            ),
        )
        return fn(states)

    def run_timeline(
        self,
        states,
        timeline,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
        start_time: float = 0.0,
    ) -> Tuple[Any, dict]:
        fn = cached_jit(
            self._run_cache,
            (
                timeline,
                float(total_time),
                float(timestep),
                int(emit_every),
                float(start_time),
            ),
            lambda: jax.jit(
                lambda s: self.ensemble.run_timeline(
                    s, timeline, total_time, timestep, emit_every,
                    start_time,
                )
            ),
        )
        return fn(states)

    def emit_state(self, states) -> dict:
        return self.ensemble.emit_state(states)

    def expanded(self, states, factor: int = 2) -> Tuple[Ensemble, Any]:
        """Device-local capacity growth for a SHARDED ensemble — the
        multi-host-safe counterpart of :meth:`Ensemble.expanded`.

        Replicates advance in lockstep and expansion appends identical
        template rows to every replicate, so the whole pad is one
        jitted, sharding-constrained concat along the row axis: no host
        gather (``Ensemble.expanded``'s ``device_get`` rejects
        non-addressable shards on a multi-host replicate mesh), no
        transient single-device copy. Bitwise-equal to the host path
        (tested) because both produce the end-appended
        ``Colony.expanded`` layout — the replicate mesh never shards the
        agent axis, so no interleave is needed.

        Returns ``(grown_ensemble, padded_sharded_states)``; callers
        re-wrap their runner around the grown ensemble as with the host
        path.
        """
        import numpy as np

        from lens_tpu.colony.colony import Colony

        ens = self.ensemble
        sim = ens.sim
        colony = getattr(sim, "colony", sim)
        if not isinstance(colony, Colony):
            raise TypeError(
                f"{type(sim).__name__} has no Colony; capacity growth "
                f"needs a Colony/SpatialColony-form sim"
            )
        spatial_form = hasattr(states, "colony")
        cs = states.colony if spatial_form else states
        # lockstep [R] step counter — read a locally addressable entry
        arr = cs.step
        if getattr(arr, "is_fully_addressable", True) is False:
            arr = arr.addressable_shards[0].data
        step_now = int(np.asarray(jax.device_get(arr)).reshape(-1)[0])
        grown_colony = colony.expanded_meta(step_now, factor)
        old_cap = colony.capacity
        b_fresh = grown_colony.capacity - old_cap
        # the ONE source of truth for template/lineage rules: exactly the
        # template[old_cap:] slice Colony.expanded pads with
        tmpl = jax.tree.map(
            lambda t: t[old_cap:], grown_colony.initial_state(0).agents
        )
        R = ens.n_replicates

        def pad(states):
            cs = states.colony if spatial_form else states

            def pad_leaf(leaf, t):
                import jax.numpy as jnp

                t = jnp.broadcast_to(
                    jnp.asarray(t).astype(leaf.dtype), (R,) + t.shape
                )
                out = jnp.concatenate([leaf, t], axis=1)
                return jax.lax.with_sharding_constraint(
                    out, self._leaf_sharding(out)
                )

            import jax.numpy as jnp

            agents = jax.tree.map(pad_leaf, cs.agents, tmpl)
            alive = jax.lax.with_sharding_constraint(
                jnp.concatenate(
                    [cs.alive, jnp.zeros((R, b_fresh), bool)], axis=1
                ),
                self._leaf_sharding(cs.alive),
            )
            new_cs = cs._replace(agents=agents, alive=alive)
            return (
                states._replace(colony=new_cs) if spatial_form else new_cs
            )

        padded = jax.jit(pad)(states)
        grown_sim = (
            sim.with_colony(grown_colony)
            if hasattr(sim, "with_colony")
            else grown_colony
        )
        return Ensemble(grown_sim, R), padded

    @property
    def n_replicates(self) -> int:
        return self.ensemble.n_replicates
