"""Shared compile/cache machinery for the sharded colony runners.

Both SPMD runners (``runner.ShardedSpatialColony``,
``multispecies.ShardedMultiSpeciesColony``) wrap a per-device block
program in ``shard_map`` + ``jit`` and cache the compiled step and run
programs. That contract — timestep pinned to the lattice's precomputed
diffusion substeps, one cached step, run programs cached per
``(total_time, timestep, emit_every)`` — lives here once so the two
runners cannot diverge.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax


def cached_jit(cache: dict, key, build):
    """Get-or-build a jitted callable in ``cache`` under ``key``.

    A fresh ``jax.jit(lambda ...)`` per call would key jit's own cache on
    the new lambda's identity and retrace every time — segmented runs
    call the same program once per segment. The cache dict is owned by
    the runner INSTANCE (a functools cache on a method would pin the
    instance and its compiled executables' device buffers in a
    class-level cache long after the owner is dropped). An unhashable
    key (e.g. a sequence-form media timeline) pays a per-call trace."""
    try:
        fn = cache.get(key)
    except TypeError:
        return build()
    if fn is None:
        fn = cache[key] = build()
    return fn


class ShardedRunnerBase:
    """Subclasses provide:

    - ``self.mesh``: the 2D (agents x space) mesh;
    - ``_lattice()``: the shared :class:`~lens_tpu.environment.lattice.Lattice`
      (timestep guard);
    - ``_pspecs(example)``: PartitionSpecs pytree for ``example`` states;
    - ``_block_step(state, timestep)``: the per-device program;
    - ``_emit_fn(carry)``: the emit slice for ``run``.
    """

    def __init__(self, mesh):
        self.mesh = mesh
        self._step = None
        self._step_dt = None
        self._step_key = None
        self._run_cache = {}

    # subclass hooks ---------------------------------------------------------

    def _lattice(self):
        raise NotImplementedError

    def _pspecs(self, example):
        raise NotImplementedError

    def _block_step(self, state, timestep: float):
        raise NotImplementedError

    def _emit_fn(self, carry) -> dict:
        raise NotImplementedError

    # shared machinery -------------------------------------------------------

    def _lattice_key(self):
        """Trace-relevant lattice parameters baked into compiled programs.

        Tests mutate the lattice post-construction (``lattice.impl = "adi"``
        etc.), so every compiled-program cache must be keyed on what the
        trace closes over: the diffusion matrix (``alpha_window`` encodes
        diffusion/timestep/dx), the scheme, the grid, and the exchange
        scaling."""
        lattice = self._lattice()
        return (
            lattice.impl,
            lattice.alpha_window.tobytes(),
            lattice.shape,
            lattice.exchange_scale,
        )

    def _assemble_fields(self, strip, s_idx):
        """Full [M, H, W] fields from this device's strip: place it in a
        zero canvas and psum over the space axis (an all-gather in psum
        clothing; psum lets the VMA checker prove the result is
        space-invariant). Runs inside shard_map; both colony runners'
        block programs start with it."""
        import jax.numpy as jnp
        from jax import lax

        from lens_tpu.parallel.mesh import SPACE_AXIS

        m, h_local, w = strip.shape
        h_full = h_local * self.n_space
        return lax.psum(
            lax.dynamic_update_slice_in_dim(
                jnp.zeros((m, h_full, w), strip.dtype), strip,
                s_idx * h_local, axis=1,
            ),
            SPACE_AXIS,
        )

    def _apply_exchange_strip(self, strip, ff, flat, contrib, s_idx):
        """Apply a block's masked, scaled exchange payload to this
        device's field strip: one plan-driven segment-sum into a full
        zero canvas, psum over the agent axis, slice this strip's rows,
        ONE >=0 clamp. The fused coupling's scatter half on a mesh —
        shared by both colony runners so the contrib/clamp numerics
        (which the bitwise fused==reference tests pin) have one
        authoritative copy. Runs inside shard_map.

        ff: the psum-assembled full fields as [M, H*W]; contrib:
        [M, rows] already alive-masked and exchange-scaled.
        """
        import jax.numpy as jnp
        from jax import lax

        from lens_tpu.ops.scatter import scatter_add_2d
        from lens_tpu.parallel.mesh import AGENTS_AXIS

        m, h_local, w = strip.shape
        delta = scatter_add_2d(jnp.zeros_like(ff), flat, contrib).reshape(
            m, h_local * self.n_space, w
        )
        delta = lax.psum(delta, AGENTS_AXIS)
        return jnp.maximum(
            strip
            + lax.dynamic_slice_in_dim(
                delta, s_idx * h_local, h_local, axis=1
            ),
            0.0,
        )

    def _diffuse_strip(self, strip, axis_name: str, n_shards: int):
        """Diffuse a sharded field strip per the lattice's ``impl``:
        ppermute-halo FTCS by default, SPIKE distributed tridiagonal ADI
        when the lattice opted into ``impl="adi"`` (one boundary exchange
        per window instead of a ppermute pair per substep; equals the
        unsharded ADI step to float rounding). Runs inside shard_map.
        """
        lattice = self._lattice()
        if lattice.impl == "adi":
            from lens_tpu.parallel.adi_spike import diffuse_adi_sharded

            # Cache keyed on the matrix the plan factors: tests mutate
            # ``lattice.impl``/parameters after construction, so a bare
            # memo would silently reuse a plan for a stale matrix.
            key = (
                lattice.alpha_window.tobytes(),
                lattice.shape,
                n_shards,
            )
            cached = getattr(self, "_spike_plan_cache", None)
            if cached is None or cached[0] != key:
                from lens_tpu.parallel.adi_spike import spike_plan

                plan = spike_plan(
                    lattice.alpha_window, *lattice.shape, n_shards=n_shards
                )
                self._spike_plan_cache = (key, plan)
            else:
                plan = cached[1]
            return diffuse_adi_sharded(strip, plan, axis_name)
        from lens_tpu.parallel.halo import diffuse_halo

        return diffuse_halo(
            strip, lattice.alpha, lattice.n_substeps, axis_name, n_shards
        )

    def step_fn(self, example, timestep: float):
        """Build the jitted shard_map step for states shaped like
        ``example``."""
        lattice = self._lattice()
        if abs(timestep - lattice.timestep) > 1e-9:
            raise ValueError(
                f"timestep={timestep} != lattice.timestep="
                f"{lattice.timestep}: the lattice precomputes its "
                f"diffusion substeps — construct it with the run timestep"
            )
        from lens_tpu.utils.platform import shard_map_fn

        specs = self._pspecs(example)
        body = shard_map_fn()(
            partial(self._block_step, timestep=timestep),
            mesh=self.mesh,
            in_specs=(specs,),
            out_specs=specs,
        )
        return jax.jit(body)

    def _cached_step(self, example, timestep: float):
        key = self._lattice_key()
        if self._step is not None and key != self._step_key:
            # The lattice was mutated after compile: the old programs bake
            # the old diffusion matrix/scheme into their graphs. Drop them
            # (run programs close over the step, so they go too).
            self._step = None
            self._run_cache.clear()
        if self._step is None:
            self._step = self.step_fn(example, timestep)
            self._step_dt = timestep
            self._step_key = key
        elif self._step_dt != timestep:
            raise ValueError(
                "timestep changed between calls; rebuild via step_fn"
            )
        return self._step

    def step(self, state, timestep: float):
        return self._cached_step(state, timestep)(state)

    def run(
        self, state, total_time: float, timestep: float, emit_every: int = 1
    ) -> Tuple[object, dict]:
        """Scan the sharded step; emit slices keep the sharded layout (no
        host round-trips inside the loop). Compiled programs cached per
        ``(total_time, timestep, emit_every)``, sharing the cached step
        with ``step()``."""
        from lens_tpu.core.schedule import scan_schedule

        step = self._cached_step(state, timestep)
        run = cached_jit(
            self._run_cache,
            (float(total_time), float(timestep), int(emit_every)),
            lambda: jax.jit(
                lambda s: scan_schedule(
                    step, self._emit_fn, s, total_time, timestep, emit_every
                )
            ),
        )
        return run(state)

    def run_timeline(
        self,
        state,
        timeline,
        total_time: float,
        timestep: float,
        emit_every: int = 1,
        start_time: float = 0.0,
    ) -> Tuple[object, dict]:
        """Run with media changes on the SHARDED path: same semantics as
        ``SpatialColony.run_timeline`` — the timeline splits the run into
        segments, each segment is one jitted sharded scan, and at each
        media EVENT the fields are rebuilt from the new recipe (host-side,
        re-placed with the state's field sharding — a few device stores
        per media switch, off the hot path).

        ``start_time`` is this call's absolute simulation time; event
        times are absolute, so a checkpoint segment covering [250, 500)
        of a t=400 shift applies the shift at 400 and does NOT re-reset
        fields at 250 (segment starts that are not event times keep the
        evolved fields).
        """
        from lens_tpu.environment.media import (
            fields_from_media,
            run_media_timeline,
        )
        from lens_tpu.parallel.distributed import place_like

        def reset_fields(s, media):
            fields = fields_from_media(self._lattice(), media)
            return s._replace(
                fields=place_like(fields, s.fields.sharding)
            )

        return run_media_timeline(
            state,
            timeline,
            total_time,
            start_time,
            run_segment=lambda s, d: self.run(s, d, timestep, emit_every),
            reset_fields=reset_fields,
        )
