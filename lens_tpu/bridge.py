"""External-simulation bridge: the CellSimulation protocol + host loop.

The reference exists to put EXTERNAL whole-cell models (wcEcoli) into
colony context: its inner agent wraps anything implementing the
CellSimulation interface — ``apply_outer_update``, ``run_incremental``,
``generate_inner_update``, ``divide``, ``finalize`` (reconstructed:
``lens/actor/inner.py``, SURVEY.md §1 L3a, §2 "wcEcoli bridge"). That
capability must survive the rebuild even though arbitrary external Python
sims cannot run inside a jitted SPMD program.

So the bridge is the framework's **host path**: the same exchange-window
semantics as ``environment.spatial.SpatialColony``, but driven step-by-
step from Python against a list of per-cell simulation objects. The
lattice math is still jax (fields on device); only the per-cell biology
runs as opaque host code. Throughput is the reference's (one Python object
per cell), which is the honest cost of opaque external models — put
anything expressible as a Process in a Compartment instead and it rides
the fast path. ``CompartmentSimulation`` adapts a Compartment to the
protocol so the two paths stay behaviorally aligned (tested against each
other), and is the template for writing a wcEcoli adapter.

Division in the host loop follows the reference handshake: a divider
returns two CellSimulation daughters; the parent is finalized and the
daughters take adjacent locations.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Protocol, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.core.state import DIVISION_SEPARATION_UM
from lens_tpu.environment.lattice import Lattice


class CellSimulation(Protocol):
    """The reference's inner-agent plug interface (SURVEY.md §1 L3a)."""

    def apply_outer_update(self, update: Mapping[str, Any]) -> None:
        """Receive the local environment (molecule -> concentration)."""
        ...

    def run_incremental(self, run_until: float) -> None:
        """Advance internal simulation time to ``run_until`` (sim-sec)."""
        ...

    def generate_inner_update(self) -> Dict[str, Any]:
        """Report state for the environment: at least ``exchange``
        (molecule -> net secreted amount since last report), and
        optionally ``volume``, ``location`` (new [2] position in um —
        the loop applies it, clipped to the domain), ``divide`` (bool)."""
        ...

    def divide(self) -> Tuple["CellSimulation", "CellSimulation"]:
        """Split into two daughters (called when divide flag is set)."""
        ...

    def finalize(self) -> None:
        """Tear down (parent after division, or experiment end)."""
        ...


class CompartmentSimulation:
    """Adapt a Compartment + wiring to the CellSimulation protocol.

    The reference's inner agent wraps its engine exactly like this; the
    adapter doubles as the template for external-model adapters (wcEcoli:
    implement the same five methods around its snapshot API).

    ``field_ports``: molecule -> (local_path, exchange_path) into the
    compartment state tree, same convention as SpatialColony.
    """

    def __init__(
        self,
        compartment,
        field_ports: Mapping[str, Tuple],
        state: Optional[dict] = None,
        time: float = 0.0,
        timestep: float = 1.0,
        divide_path: Tuple[str, ...] = ("global", "divide"),
        key: Optional[jax.Array] = None,
    ):
        from lens_tpu.core.topology import normalize_path
        from lens_tpu.utils.dicts import get_path

        self.compartment = compartment
        self.field_ports = {
            mol: (normalize_path(p[0]), normalize_path(p[1]))
            for mol, p in field_ports.items()
        }
        self.state = state if state is not None else compartment.initial_state()
        self.time = float(time)
        self.timestep = float(timestep)
        self.divide_path = normalize_path(divide_path)
        self.key = key if key is not None else jax.random.PRNGKey(0)
        self._get_path = get_path

    def apply_outer_update(self, update: Mapping[str, Any]) -> None:
        from lens_tpu.utils.dicts import set_path

        for mol, conc in update.items():
            if mol in self.field_ports:
                local, _ = self.field_ports[mol]
                self.state = set_path(
                    self.state, local, jnp.asarray(conc, jnp.float32)
                )

    def run_incremental(self, run_until: float) -> None:
        while self.time < run_until - 1e-9:
            if self.compartment.has_stochastic:
                self.key, sub = jax.random.split(self.key)
                self.state = self.compartment.step(
                    self.state, self.timestep, sub
                )
            else:
                self.state = self.compartment.step(self.state, self.timestep)
            self.time += self.timestep

    def generate_inner_update(self) -> Dict[str, Any]:
        from lens_tpu.utils.dicts import set_path

        exchange: Dict[str, float] = {}
        for mol, (_, exch) in self.field_ports.items():
            exchange[mol] = float(self._get_path(self.state, exch))
            self.state = set_path(self.state, exch, jnp.asarray(0.0))
        update: Dict[str, Any] = {"exchange": exchange}
        try:
            update["divide"] = bool(
                float(self._get_path(self.state, self.divide_path)) > 0
            )
        except KeyError:
            update["divide"] = False
        try:
            update["volume"] = float(
                self._get_path(self.state, ("global", "volume"))
            )
        except KeyError:
            pass
        return update

    def divide(self):
        self.key, sub = jax.random.split(self.key)
        a, b = self.compartment.divide(self.state, sub)
        return (
            CompartmentSimulation(
                self.compartment, self.field_ports, a, self.time,
                self.timestep, self.divide_path, jax.random.fold_in(sub, 0),
            ),
            CompartmentSimulation(
                self.compartment, self.field_ports, b, self.time,
                self.timestep, self.divide_path, jax.random.fold_in(sub, 1),
            ),
        )

    def finalize(self) -> None:
        pass


class ExternalSnapshotAdapter:
    """CellSimulation adapter for snapshot-API external models (wcEcoli
    shape): proof that the five-method protocol generalizes beyond
    ``Compartment`` (SURVEY.md §2 "wcEcoli bridge").

    The external model is any object with the snapshot-style surface the
    whole-cell lineage exposes:

    - ``set_media({molecule: concentration})`` — environment in;
    - ``advance_to(t)`` — run internal simulation to absolute time t;
    - ``get_snapshot() -> dict`` with at least ``exchange_totals``
      ({molecule: CUMULATIVE net secretion since birth}) and optionally
      ``volume`` and ``ready_to_divide``;
    - ``divide_snapshot() -> (snapshot_a, snapshot_b)`` — daughter
      snapshots;
    - a ``model_factory(snapshot)`` (passed to this adapter) that boots a
      new model instance from a daughter snapshot.

    The adapter owns the cumulative->per-window exchange differencing
    (external models account since birth; the exchange loop wants this
    window's delta), so external code needs no knowledge of exchange
    windows at all.
    """

    def __init__(self, model, model_factory):
        self.model = model
        self.model_factory = model_factory
        # Seed the differencing baseline from the model's CURRENT totals:
        # a model attached mid-life (checkpoint restore, or a daughter
        # snapshot that carries cumulative accounting forward) must not
        # have its whole lifetime exchange scattered into the first
        # window.
        snap = model.get_snapshot()
        self._last_totals: Dict[str, float] = dict(
            snap.get("exchange_totals", {})
        )

    def apply_outer_update(self, update: Mapping[str, Any]) -> None:
        self.model.set_media(dict(update))

    def run_incremental(self, run_until: float) -> None:
        self.model.advance_to(float(run_until))

    def generate_inner_update(self) -> Dict[str, Any]:
        snap = self.model.get_snapshot()
        totals = dict(snap.get("exchange_totals", {}))
        exchange = {
            mol: total - self._last_totals.get(mol, 0.0)
            for mol, total in totals.items()
        }
        self._last_totals = totals
        update: Dict[str, Any] = {"exchange": exchange}
        update["divide"] = bool(snap.get("ready_to_divide", False))
        if "volume" in snap:
            update["volume"] = float(snap["volume"])
        return update

    def divide(self):
        snap_a, snap_b = self.model.divide_snapshot()
        return (
            ExternalSnapshotAdapter(
                self.model_factory(snap_a), self.model_factory
            ),
            ExternalSnapshotAdapter(
                self.model_factory(snap_b), self.model_factory
            ),
        )

    def finalize(self) -> None:
        close = getattr(self.model, "close", None)
        if close is not None:
            close()


class HostAgent:
    """Bookkeeping for one cell in the host loop (id, sim, location).

    ``parent_id``/``birth_time`` mirror the fast path's lineage emit
    (colony layer): both daughters of a division are NEW agents carrying
    their parent's id, so host-loop experiments reconstruct the same
    lineage trees the colony trajectories do."""

    _next_id = 0

    def __init__(
        self,
        sim: CellSimulation,
        location: Sequence[float],
        parent_id: Optional[str] = None,
        birth_time: float = 0.0,
    ):
        self.sim = sim
        self.location = np.asarray(location, np.float64)
        self.agent_id = f"agent_{HostAgent._next_id}"
        HostAgent._next_id += 1
        self.parent_id = parent_id
        self.birth_time = float(birth_time)


class HostExchangeLoop:
    """The reference's outer/inner exchange loop, host-driven.

    Runs external CellSimulations against a (device-resident) lattice in
    discrete exchange windows: gather local concentrations -> each sim
    runs incrementally -> apply exchanges -> diffuse -> handle divisions.
    This is behaviorally the loop in SURVEY.md §3.2 minus Kafka.
    """

    def __init__(
        self,
        lattice: Lattice,
        exchange_window: float = 1.0,
        seed: int = 0,
    ):
        self.lattice = lattice
        self.window = float(exchange_window)
        self.fields = lattice.initial_fields()
        self.agents: List[HostAgent] = []
        self.time = 0.0
        self._rng = np.random.default_rng(seed)  # division placement axes

    def add_agent(self, sim: CellSimulation, location: Sequence[float]) -> str:
        agent = HostAgent(sim, location)
        self.agents.append(agent)
        return agent.agent_id

    def _locations(self) -> jnp.ndarray:
        if not self.agents:
            return jnp.zeros((0, 2), jnp.float32)
        return jnp.asarray(
            np.stack([a.location for a in self.agents]), jnp.float32
        )

    def step(self) -> None:
        """One exchange window for every agent + the environment."""
        target = self.time + self.window
        locations = self._locations()
        alive = jnp.ones((len(self.agents),), bool)
        if self.agents:
            local = self.lattice.local_concentrations(
                self.fields, locations, alive
            )  # [N, M]
            # outer -> inner
            for k, agent in enumerate(self.agents):
                agent.sim.apply_outer_update(
                    {
                        mol: float(local[k, m])
                        for m, mol in enumerate(self.lattice.molecules)
                    }
                )
                agent.sim.run_incremental(target)
            # inner -> outer (the barrier is the loop structure itself)
            updates = [a.sim.generate_inner_update() for a in self.agents]
            exchange = jnp.asarray(
                [
                    [u["exchange"].get(mol, 0.0) for mol in self.lattice.molecules]
                    for u in updates
                ],
                jnp.float32,
            )
            self.fields = self.lattice.apply_exchanges(
                self.fields, locations, exchange, alive
            )
            # Motility: an inner update may report a new location (the
            # reference's generate_inner_update carries cell geometry,
            # SURVEY.md §3.2); clip onto the domain like the device path.
            hi = np.asarray(self.lattice.size) - 1e-3
            for agent, update in zip(self.agents, updates):
                if "location" in update:
                    agent.location = np.clip(
                        np.asarray(update["location"], np.float64), 0.0, hi
                    )
            self._handle_divisions(updates)
        self.fields = self.lattice.step_fields(self.fields)
        self.time = target

    def _handle_divisions(self, updates: List[Mapping]) -> None:
        new_agents: List[HostAgent] = []
        for agent, update in zip(list(self.agents), updates):
            if not update.get("divide"):
                new_agents.append(agent)
                continue
            sim_a, sim_b = agent.sim.divide()
            agent.sim.finalize()
            # Same placement rule as the colony fast path's `offset`
            # divider (core.state._div_offset): daughters separate by one
            # cell length along a uniformly random axis.
            theta = self._rng.uniform(0.0, 2.0 * np.pi)
            half = (DIVISION_SEPARATION_UM / 2.0) * np.asarray(
                [np.cos(theta), np.sin(theta)]
            )
            hi = np.asarray(self.lattice.size) - 1e-3
            new_agents.append(
                HostAgent(
                    sim_a, np.clip(agent.location + half, 0.0, hi),
                    parent_id=agent.agent_id, birth_time=self.time,
                )
            )
            new_agents.append(
                HostAgent(
                    sim_b, np.clip(agent.location - half, 0.0, hi),
                    parent_id=agent.agent_id, birth_time=self.time,
                )
            )
        self.agents = new_agents

    def run(self, total_time: float) -> None:
        n = int(round(total_time / self.window))
        for _ in range(n):
            self.step()
