"""Minimal unit handling for biochemical state.

The reference carries a units library through its parameter plumbing
(reconstructed: ``lens/utils/units.py``, SURVEY.md §2 "Utils" — mount
empty, see SURVEY header). A full dimensional-analysis object system would
fight ``jit`` (units-on-arrays means wrapper pytrees everywhere), so the
rebuild adopts the standard JAX stance: **state arrays are plain floats in
canonical units; unit handling happens at the parameter/config boundary.**

Canonical units used throughout the framework:

========== ======================= =========================
quantity   canonical unit          note
========== ======================= =========================
time       second (s)              engine timesteps
length     micrometer (um)         lattice geometry
volume     femtoliter (fL)         1 um^3 == 1 fL
amount     molecule counts         discrete species
conc.      millimolar (mM)         field + ODE species
mass       femtogram (fg)          cell dry mass
rate       1/s                     first-order constants
========== ======================= =========================

This module provides the conversion constants and the count<->concentration
helpers every deriver/process needs, so magic numbers never appear inline.
"""

from __future__ import annotations

import jax.numpy as jnp

#: Avogadro's number (1/mol).
AVOGADRO = 6.02214076e23

#: Molecule counts per femtoliter at 1 mM.
#: 1 mM = 1e-3 mol/L; 1 fL = 1e-15 L -> 1e-18 mol/fL -> x N_A counts/fL.
COUNTS_PER_FL_PER_MM = AVOGADRO * 1e-18  # ~6.022e5

#: Seconds per minute / hour (timeline configs are often written in min).
MINUTE = 60.0
HOUR = 3600.0

#: E. coli-ish cytoplasmic density, fg dry mass per fL of cell volume.
#: (~1.1 g/mL wet with ~30% dry fraction -> ~330 fg/fL; the reference's
#: deriver uses a single density constant the same way.)
CELL_DENSITY_FG_PER_FL = 330.0


def counts_to_millimolar(counts, volume_fl):
    """Convert molecule counts to mM given cell volume in fL."""
    return counts / (COUNTS_PER_FL_PER_MM * volume_fl)


def millimolar_to_counts(conc_mm, volume_fl):
    """Convert a mM concentration to (real-valued) molecule counts."""
    return conc_mm * COUNTS_PER_FL_PER_MM * volume_fl


def volume_from_mass(mass_fg, density_fg_per_fl=CELL_DENSITY_FG_PER_FL):
    """Cell volume (fL) from dry mass (fg) at constant density."""
    return mass_fg / density_fg_per_fl


def mass_from_volume(volume_fl, density_fg_per_fl=CELL_DENSITY_FG_PER_FL):
    """Cell dry mass (fg) from volume (fL) at constant density."""
    return volume_fl * density_fg_per_fl


def doubling_time_to_rate(doubling_time_s):
    """Exponential growth rate (1/s) from a doubling time (s)."""
    return jnp.log(2.0) / doubling_time_s
