"""Backend platform guards for this box's flaky ``axon`` TPU relay.

The environment injects an ``axon`` PJRT hook (sitecustomize via
PYTHONPATH) that forces ``jax_platforms="axon,cpu"`` and ignores the
``JAX_PLATFORMS`` environment variable; when the tunnel relay is down,
backend init blocks in a retry loop. Setting the jax *config* after
import but before backend init does win over the hook — the plugin stays
registered but is never initialized, so nothing dials the relay.

One canonical copy of that guard lives here; ``tests/conftest.py`` keeps
its own pre-import copy because it must also set ``XLA_FLAGS`` before
pytest imports anything else.
"""

from __future__ import annotations

import os


def backend_probe_hangs(timeout: float = 90.0) -> bool:
    """Does accelerator backend init HANG in this environment?

    Runs ``jax.devices()`` in a throwaway child process with a timeout —
    a dead relay blocks init in a retry loop, which is indistinguishable
    from slow init except by waiting. Only a hang returns True; fast
    failures return False so callers can surface the real error text.
    Costs one extra backend init when healthy; use at the top of
    long-running bench scripts, not in the library.
    """
    import subprocess
    import sys

    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True,
            timeout=timeout,
        )
        return False
    except subprocess.TimeoutExpired:
        return True


def guard_accelerator_or_exit() -> None:
    """Bench-script preamble: refuse to start against a hung relay.

    - ``BENCH_FORCE_CPU=1``: pin the CPU platform and return (no probe)
      — the documented escape hatch actually forces CPU everywhere.
    - Otherwise, if backend init hangs (``BENCH_PROBE_TIMEOUT`` seconds,
      default 90), exit with an explanation instead of wedging; a probe
      that fails FAST falls through so the run surfaces the real error.
    """
    if os.environ.get("BENCH_FORCE_CPU"):
        force_cpu_platform(1)
        return
    try:
        timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", 90.0))
    except ValueError:
        timeout = 90.0
    if backend_probe_hangs(timeout):
        raise SystemExit(
            "accelerator backend init hung (relay down?) — rerun when the "
            "chip is reachable, or set BENCH_FORCE_CPU=1"
        )


def force_cpu_platform(n_devices: int = 1) -> bool:
    """Pin jax to the CPU platform with ``n_devices`` virtual host devices.

    Must run before jax backend init (import order does not matter; first
    device use does). Returns True if the platform was pinned, False if a
    backend was already initialized (in which case we leave it alone
    rather than raise — callers degrade to whatever devices exist).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        return True
    except RuntimeError:
        return False


def shard_map_fn():
    """``shard_map`` across jax versions: the stable ``jax.shard_map``
    (jax >= 0.6) when present, else the ``jax.experimental`` original
    (same call signature for the mesh/in_specs/out_specs form every
    caller here uses). The sharded runners went dead-on-arrival on a
     0.4.x jaxlib without this — every ``jax.shard_map`` call raised
    AttributeError before any collective ran."""
    import jax

    fn = getattr(jax, "shard_map", None)
    version = tuple(int(x) for x in jax.__version__.split(".")[:2])
    # The attribute alone is not proof of the stable API: the test
    # conftest back-patches ``jax.shard_map`` for old jaxlibs, and that
    # patched-in experimental function still defaults check_rep=True.
    if fn is not None and version >= (0, 6):
        return fn
    import functools

    from jax.experimental.shard_map import shard_map

    # check_rep=False: the experimental checker has no replication rule
    # for ``while`` (the LP solvers scan one), and the runners' programs
    # are replication-correct by construction (psum-assembled fields);
    # the stable jax.shard_map drops the knob entirely.
    @functools.wraps(shard_map)
    def compat(f, *, mesh, in_specs, out_specs):
        return shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

    return compat
