"""Backend platform guards for this box's flaky ``axon`` TPU relay.

The environment injects an ``axon`` PJRT hook (sitecustomize via
PYTHONPATH) that forces ``jax_platforms="axon,cpu"`` and ignores the
``JAX_PLATFORMS`` environment variable; when the tunnel relay is down,
backend init blocks in a retry loop. Setting the jax *config* after
import but before backend init does win over the hook — the plugin stays
registered but is never initialized, so nothing dials the relay.

One canonical copy of that guard lives here; ``tests/conftest.py`` keeps
its own pre-import copy because it must also set ``XLA_FLAGS`` before
pytest imports anything else.
"""

from __future__ import annotations

import os


def force_cpu_platform(n_devices: int = 1) -> bool:
    """Pin jax to the CPU platform with ``n_devices`` virtual host devices.

    Must run before jax backend init (import order does not matter; first
    device use does). Returns True if the platform was pinned, False if a
    backend was already initialized (in which case we leave it alone
    rather than raise — callers degrade to whatever devices exist).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
        return True
    except RuntimeError:
        return False
