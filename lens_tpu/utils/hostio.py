"""Async device->host transfer helpers shared by every segment loop.

Three places move emitted trajectories off the device while the next
chunk of compute is already in flight — the ``Experiment`` segment
loop, the serve layer's window streamer (``lens_tpu.serve.streamer``),
and the sweep ensemble backend's chunk loop. They all want the same
two-step dance:

1. :func:`copy_tree_to_host_async` right after dispatching the NEXT
   device program — every leaf starts its DMA immediately, so the
   transfer rides alongside the in-flight compute instead of after it;
2. a later ``jax.device_get`` (or numpy coercion) that finds the bytes
   already host-side and returns without a device round-trip.

Keeping the helper in one place pins the policy: the async copy is a
pure hint (arrays without ``copy_to_host_async`` — numpy leaves,
older jax — are silently fine), and it never changes bits, only WHEN
the transfer happens.
"""

from __future__ import annotations

from typing import Any

import jax


def copy_tree_to_host_async(tree: Any) -> Any:
    """Start a device->host copy of every array leaf; returns ``tree``
    unchanged (the handles still resolve via ``jax.device_get``).

    Safe on any pytree: leaves lacking ``copy_to_host_async`` (numpy
    arrays, scalars) are skipped. Callers dispatch their next device
    program FIRST, then call this, then do host work — the eventual
    ``device_get`` overlaps both.
    """
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "copy_to_host_async"):
            leaf.copy_to_host_async()
    return tree
