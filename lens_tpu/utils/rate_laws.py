"""Rate-law helpers shared by kinetic processes.

The reference centralizes Michaelis–Menten / Hill / mass-action rate
construction in its utils so each kinetic Process declares parameters, not
formulas (reconstructed: ``lens/utils/`` rate-law helpers, SURVEY.md §2
"Utils"). All helpers here are pure ``jnp`` expressions — safe under
``jit``/``vmap``/``grad`` — and guard denominators so XLA never sees a
0/0 (which would poison a whole vmapped batch with NaNs).
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12


def michaelis_menten(s, vmax, km):
    """v = vmax * s / (km + s), clamped for s <= 0."""
    s = jnp.maximum(s, 0.0)
    return vmax * s / (km + s + _EPS)


def competitive_inhibition(s, i, vmax, km, ki):
    """MM rate with competitive inhibitor i: km' = km * (1 + i/ki)."""
    s = jnp.maximum(s, 0.0)
    i = jnp.maximum(i, 0.0)
    return vmax * s / (km * (1.0 + i / (ki + _EPS)) + s + _EPS)


def hill(s, vmax, k, n):
    """Hill activation: v = vmax * s^n / (k^n + s^n)."""
    s = jnp.maximum(s, 0.0)
    sn = s**n
    return vmax * sn / (k**n + sn + _EPS)


def hill_repression(s, vmax, k, n):
    """Hill repression: v = vmax * k^n / (k^n + s^n)."""
    s = jnp.maximum(s, 0.0)
    kn = k**n
    return vmax * kn / (kn + s**n + _EPS)


def mass_action(rate, *concentrations):
    """v = rate * prod(concentrations) (each clamped at 0)."""
    v = rate
    for c in concentrations:
        v = v * jnp.maximum(c, 0.0)
    return v


def first_order(rate, s):
    """v = rate * s, clamped at 0."""
    return rate * jnp.maximum(s, 0.0)
