"""Nested-dict helpers.

The reference keeps all simulation state and configuration in nested dicts
merged through boot functions (reconstructed: ``lens/utils/dict_utils.py``,
SURVEY.md §2). The rebuild keeps the same deep-merge semantics because the
state tree IS a JAX pytree of nested dicts: these helpers are the only
"schema language" the engine needs.

All functions are pure and operate on plain dicts, so they are safe to call
at trace time inside ``jit`` (the dict structure is static; only leaves are
traced arrays).
"""

from __future__ import annotations

from typing import Any, Iterator, Mapping, Sequence, Tuple

Path = Tuple[str, ...]


def deep_merge(base: dict, override: Mapping | None) -> dict:
    """Recursively merge ``override`` into a copy of ``base``.

    Dicts merge key-wise; any non-dict leaf in ``override`` replaces the
    corresponding value in ``base``. Mirrors the reference's config-merge
    behavior (agent type defaults <- experiment overrides).
    """
    if override is None:
        return dict(base)
    out = dict(base)
    for key, value in override.items():
        if key in out and isinstance(out[key], dict) and isinstance(value, Mapping):
            out[key] = deep_merge(out[key], value)
        else:
            out[key] = value
    return out


def get_path(tree: Mapping, path: Sequence[str]) -> Any:
    """Fetch the value at a nested ``path`` (tuple of keys) in ``tree``."""
    node: Any = tree
    for key in path:
        node = node[key]
    return node


def set_path(tree: dict, path: Sequence[str], value: Any) -> dict:
    """Return a copy of ``tree`` with ``value`` stored at nested ``path``.

    Copy-on-write along the path only — siblings are shared, which keeps
    this cheap at trace time and referentially transparent for JAX.
    """
    if not path:
        if not isinstance(value, Mapping):
            raise ValueError("cannot replace the root with a non-mapping")
        return dict(value)
    out = dict(tree)
    node = out
    for key in path[:-1]:
        child = node.get(key, {})
        if not isinstance(child, Mapping):
            raise KeyError(f"path {tuple(path)} crosses non-dict node at {key!r}")
        child = dict(child)
        node[key] = child
        node = child
    node[path[-1]] = value
    return out


def flatten_paths(tree: Mapping, prefix: Path = ()) -> Iterator[Tuple[Path, Any]]:
    """Yield ``(path, leaf)`` for every non-dict leaf in ``tree``."""
    for key, value in tree.items():
        path = prefix + (key,)
        if isinstance(value, Mapping):
            yield from flatten_paths(value, path)
        else:
            yield path, value
