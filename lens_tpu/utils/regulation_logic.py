"""Boolean regulation-rule parser compiling to jnp-traceable closures.

The reference parses boolean gene/flux regulation rules from its flat-file
knowledge base — strings like ``"not (glucose external)"`` deciding
whether a reaction or gene is active (reconstructed:
``lens/utils/regulation_logic.py``, SURVEY.md §2 "Utils"; the
Covert-Palsson 2002 regulated-metabolism lineage works exactly this way).

The rebuild compiles each rule ONCE at construction into a pure closure
``rule(env: Mapping[str, Array]) -> Array`` of soft-boolean floats
(0.0/1.0), built only from ``jnp`` ops — so rules evaluate inside
``jit``/``vmap`` with no Python branching on data. Presence thresholds
turn analog values into booleans: ``x`` is "on" when ``x > threshold``.

Grammar (case-insensitive keywords)::

    rule     := or_expr
    or_expr  := and_expr ("or" and_expr)*
    and_expr := not_expr ("and" not_expr)*
    not_expr := "not" not_expr | atom
    atom     := "(" or_expr ")" | name | comparison
    comparison := name (">" | "<" | ">=" | "<=") number

Names may contain letters, digits, ``_``, ``-`` and ``[]`` (compartment
tags like ``glc[e]``).
"""

from __future__ import annotations

import re
from typing import Callable, List, Mapping, Sequence

import jax.numpy as jnp

_TOKEN = re.compile(
    r"\s*(?:(?P<lparen>\()|(?P<rparen>\))|(?P<op>>=|<=|>|<)"
    r"|(?P<number>-?\d+(?:\.\d+)?(?:[eE]-?\d+)?)"
    r"|(?P<name>[A-Za-z_][A-Za-z0-9_\-\[\]]*))"
)

_KEYWORDS = {"and", "or", "not"}

#: Default presence threshold: a species is "present" when value > this.
DEFAULT_THRESHOLD = 0.5


class Rule:
    """A compiled regulation rule: callable on a dict of named arrays."""

    def __init__(self, source: str, names: Sequence[str], fn: Callable):
        self.source = source
        self.names = tuple(names)
        self._fn = fn

    def __call__(self, env: Mapping) -> jnp.ndarray:
        missing = [n for n in self.names if n not in env]
        if missing:
            raise KeyError(
                f"rule {self.source!r} needs species {missing} "
                f"not present in the evaluation environment"
            )
        return self._fn(env)

    def __repr__(self):
        return f"Rule({self.source!r}, names={self.names})"


def _tokenize(text: str) -> List[str]:
    tokens: List[str] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m or m.end() == pos:
            if text[pos:].strip():
                raise ValueError(
                    f"cannot tokenize rule at {text[pos:]!r} (full rule: {text!r})"
                )
            break
        pos = m.end()
        for kind in ("lparen", "rparen", "op", "number", "name"):
            val = m.group(kind)
            if val is not None:
                # keywords are case-insensitive; species names keep their case
                if kind == "name" and val.lower() in _KEYWORDS:
                    val = val.lower()
                tokens.append(val)
                break
    return tokens


class _Parser:
    def __init__(self, tokens: List[str], threshold: float):
        self.tokens = tokens
        self.pos = 0
        self.threshold = threshold
        self.names: List[str] = []

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def take(self):
        tok = self.peek()
        self.pos += 1
        return tok

    def parse(self):
        fn = self.or_expr()
        if self.peek() is not None:
            raise ValueError(f"trailing tokens in rule: {self.tokens[self.pos:]}")
        return fn

    def or_expr(self):
        terms = [self.and_expr()]
        while self.peek() == "or":
            self.take()
            terms.append(self.and_expr())
        if len(terms) == 1:
            return terms[0]
        return lambda env, terms=terms: jnp.clip(
            sum(t(env) for t in terms), 0.0, 1.0
        )

    def and_expr(self):
        terms = [self.not_expr()]
        while self.peek() == "and":
            self.take()
            terms.append(self.not_expr())
        if len(terms) == 1:
            return terms[0]

        def all_of(env, terms=terms):
            out = terms[0](env)
            for t in terms[1:]:
                out = out * t(env)
            return out

        return all_of

    def not_expr(self):
        if self.peek() == "not":
            self.take()
            inner = self.not_expr()
            return lambda env, inner=inner: 1.0 - inner(env)
        return self.atom()

    def atom(self):
        tok = self.peek()
        if tok == "(":
            self.take()
            inner = self.or_expr()
            if self.take() != ")":
                raise ValueError("unbalanced parenthesis in rule")
            return inner
        if tok is None:
            raise ValueError("unexpected end of rule")
        if tok in _KEYWORDS:
            raise ValueError(f"unexpected keyword {tok!r}")
        name = self.take()
        if name not in self.names:
            self.names.append(name)
        nxt = self.peek()
        if nxt in (">", "<", ">=", "<="):
            op = self.take()
            num_tok = self.take()
            try:
                num = float(num_tok)
            except (TypeError, ValueError):
                raise ValueError(
                    f"comparison {name} {op} expects a number, got {num_tok!r}"
                )
            cmp = {
                ">": lambda x: x > num,
                "<": lambda x: x < num,
                ">=": lambda x: x >= num,
                "<=": lambda x: x <= num,
            }[op]
            return lambda env, name=name, cmp=cmp: jnp.asarray(
                cmp(env[name]), jnp.float32
            )
        thr = self.threshold
        return lambda env, name=name, thr=thr: jnp.asarray(
            env[name] > thr, jnp.float32
        )


def compile_rule(source: str, threshold: float = DEFAULT_THRESHOLD) -> Rule:
    """Compile a boolean rule string into a jnp-traceable :class:`Rule`.

    >>> rule = compile_rule("not repressor")
    >>> float(rule({"repressor": jnp.asarray(0.0)}))
    1.0
    """
    if not source or not source.strip():
        # empty rule == constitutively on
        return Rule(source, (), lambda env: jnp.asarray(1.0, jnp.float32))
    parser = _Parser(_tokenize(source), threshold)
    fn = parser.parse()
    return Rule(source, parser.names, fn)
