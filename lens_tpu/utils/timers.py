"""Tracing/profiling: phase timers + jax profiler hooks.

The reference has no dedicated tracing — wall-clock logging at agent level
at best (SURVEY.md §5 "Tracing/profiling"); the rebuild ships the TPU
equivalents: phase timers that fence on ``block_until_ready`` (an async
dispatch means un-fenced timings measure nothing) and a context manager
around ``jax.profiler`` for on-demand XLA traces viewable in
TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

import jax


class PhaseTimer:
    """Accumulate wall-clock per named phase, fencing device work.

    >>> timer = PhaseTimer()
    >>> with timer.phase("step", fence=state):
    ...     state = step(state)
    >>> timer.summary()
    {'step': {'total_s': ..., 'calls': 1, 'mean_s': ...}}

    ``fence`` (any pytree of arrays) is blocked on AFTER the body, so the
    recorded time includes the device execution the body dispatched —
    pass the phase's OUTPUT. Without a fence the timing is dispatch-only.
    """

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.calls: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str, fence: Any = None) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            if fence is not None:
                jax.block_until_ready(fence)
            elapsed = time.perf_counter() - start
            self.totals[name] = self.totals.get(name, 0.0) + elapsed
            self.calls[name] = self.calls.get(name, 0) + 1

    def timed(self, name: str, fn, *args, **kwargs):
        """Run ``fn`` under the timer, fencing on its result; return it."""
        start = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        elapsed = time.perf_counter() - start
        self.totals[name] = self.totals.get(name, 0.0) + elapsed
        self.calls[name] = self.calls.get(name, 0) + 1
        return out

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {
                "total_s": total,
                "calls": self.calls[name],
                "mean_s": total / self.calls[name],
            }
            for name, total in self.totals.items()
        }

    def report(self) -> str:
        lines = []
        for name, s in sorted(
            self.summary().items(), key=lambda kv: -kv[1]["total_s"]
        ):
            lines.append(
                f"{name:30s} {s['total_s']:9.3f}s total  "
                f"{s['calls']:6d} calls  {s['mean_s'] * 1e3:9.3f} ms/call"
            )
        return "\n".join(lines)


@contextlib.contextmanager
def xla_trace(log_dir: str = "/tmp/lens_tpu_trace") -> Iterator[str]:
    """Capture an XLA profiler trace for the enclosed block.

    View with TensorBoard's profile plugin or ui.perfetto.dev. Device ops
    inside the block must complete inside it (fence before exit) to land
    in the trace.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
