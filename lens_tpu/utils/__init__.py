from lens_tpu.utils.dicts import deep_merge, get_path, set_path, flatten_paths

__all__ = ["deep_merge", "get_path", "set_path", "flatten_paths"]
