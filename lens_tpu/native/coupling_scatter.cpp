// Native CPU segment/scatter-add for the fused agent<->lattice coupling
// (ops.scatter.scatter_add_2d).
//
// XLA's CPU scatter lowers to a generic serial update loop measured at
// ~35-45 ns per update on this class of host — at config-2 scale
// (10k agents x 2 scatters x every step) that loop IS the coupling
// phase (BENCH_PHASES_CPU_r07.json "reference" rows). This kernel is
// the same left-fold in the same row order (bitwise-identical results,
// asserted in tests/test_spatial.py), minus the generic-scatter
// machinery: ~1-2 ns per update.
//
// Contract (enforced by the ffi binding + the Python dispatcher):
//   base [C, B] f32, idx [N] s32, upd [C, N] f32 -> out [C, B] f32
//   out = base; for c: for n: out[c, idx[n]] += upd[c, n]
// Out-of-range indices are dropped (XLA scatter's OOB semantics; the
// callers clip anyway). base is input-output aliased, so the copy below
// only runs when XLA actually materialized a distinct output buffer.

#include <cstdint>
#include <cstring>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error ScatterAddImpl(ffi::Buffer<ffi::F32> base,
                                 ffi::Buffer<ffi::S32> idx,
                                 ffi::Buffer<ffi::F32> upd,
                                 ffi::ResultBuffer<ffi::F32> out) {
  auto base_dims = base.dimensions();
  auto upd_dims = upd.dimensions();
  if (base_dims.size() != 2 || upd_dims.size() != 2 ||
      idx.dimensions().size() != 1) {
    return ffi::Error::InvalidArgument(
        "scatter_add expects base [C, B], idx [N], upd [C, N]");
  }
  const size_t channels = base_dims[0];
  const size_t bins = base_dims[1];
  const size_t n = idx.dimensions()[0];
  if (upd_dims[0] != channels || upd_dims[1] != n) {
    return ffi::Error::InvalidArgument(
        "upd shape does not match (base channels, idx length)");
  }
  float* o = out->typed_data();
  const float* b = base.typed_data();
  if (o != b) std::memcpy(o, b, channels * bins * sizeof(float));
  const int32_t* ix = idx.typed_data();
  const float* u = upd.typed_data();
  for (size_t c = 0; c < channels; ++c) {
    float* oc = o + c * bins;
    const float* uc = u + c * n;
    for (size_t i = 0; i < n; ++i) {
      const int32_t k = ix[i];
      if (k >= 0 && static_cast<size_t>(k) < bins) oc[k] += uc[i];
    }
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    LensCouplingScatterAdd, ScatterAddImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()   // base [C, B]
        .Arg<ffi::Buffer<ffi::S32>>()   // idx [N]
        .Arg<ffi::Buffer<ffi::F32>>()   // upd [C, N]
        .Ret<ffi::Buffer<ffi::F32>>()); // out [C, B]
