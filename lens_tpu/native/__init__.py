"""Native (C++) runtime components and their ctypes bindings.

The reference's runtime leans on third-party native code — librdkafka for
transport, MongoDB for the emit sink (SURVEY.md §2 "native components").
The transport disappears in the rebuild (stacked state + collectives);
the emit sink's native piece lives here: ``emit_writer.cpp``, a
background-thread record writer the Python emitter drives through ctypes.

The shared library is built on first use with the repo's Makefile (g++ is
part of the baked toolchain); if the build fails for any reason the
caller falls back to a pure-Python writer with identical file format —
functionality is never blocked on the native path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_NATIVE_DIR, "libemit_writer.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    """Build the shared library if missing; True on success."""
    if os.path.exists(_SO_PATH):
        return True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_SO_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def emit_writer_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first call.

    Returns None (and remembers the failure) when the toolchain is
    unavailable — callers must fall back to the Python writer.
    """
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _build_lock:
        if _lib is not None or _build_failed:
            return _lib
        if not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.ew_open.argtypes = [ctypes.c_char_p]
        lib.ew_open.restype = ctypes.c_void_p
        lib.ew_write.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.ew_write.restype = ctypes.c_int
        lib.ew_flush.argtypes = [ctypes.c_void_p]
        lib.ew_flush.restype = ctypes.c_int
        lib.ew_close.argtypes = [ctypes.c_void_p]
        lib.ew_close.restype = ctypes.c_int
        lib.ew_error.argtypes = [ctypes.c_void_p]
        lib.ew_error.restype = ctypes.c_char_p
        _lib = lib
        return _lib
