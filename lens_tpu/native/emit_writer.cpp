// Native emit sink: a background-thread record writer.
//
// The reference's emit path hands every agent's timeseries row to MongoDB
// through a C++ client (reconstructed: SURVEY.md §2 "native components" —
// MongoDB is the emit sink; §5 "Metrics/logging"). The rebuild replaces
// the database with an append-only record log on local disk, and this
// file is the native piece: a lock-guarded ring of pending buffers
// drained by a writer thread, so the simulation's host thread never
// blocks on disk I/O (SURVEY.md §7 hard parts: "Emitter without killing
// throughput").
//
// Record framing (little-endian, written atomically per record):
//   u32 magic 0x4C454E53 ("LENS"), u32 crc32 of payload, u64 payload len,
//   payload bytes.
// The Python side (lens_tpu/emit/log.py) owns payload encoding; this
// layer moves bytes.
//
// C ABI (ctypes): ew_open / ew_write / ew_flush / ew_close / ew_error.

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x4C454E53;  // "LENS"
constexpr size_t kMaxQueueBytes = 256u << 20;  // 256 MiB backpressure cap

uint32_t crc32_table[256];
bool crc32_init_done = false;

void crc32_init() {
  if (crc32_init_done) return;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc32_table[i] = c;
  }
  crc32_init_done = true;
}

uint32_t crc32(const uint8_t* data, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; ++i)
    c = crc32_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Writer {
  FILE* file = nullptr;
  std::thread thread;
  std::mutex mu;
  std::condition_variable cv;        // signals the writer thread
  std::condition_variable drained;   // signals flush/backpressure waiters
  std::deque<std::vector<uint8_t>> queue;
  size_t queued_bytes = 0;
  bool stop = false;
  bool io_error = false;
  std::string error;

  void run() {
    for (;;) {
      std::vector<uint8_t> rec;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv.wait(lock, [&] { return stop || !queue.empty(); });
        if (queue.empty()) {
          if (stop) return;
          continue;
        }
        rec = std::move(queue.front());
        queue.pop_front();
        queued_bytes -= rec.size();
      }
      if (!io_error) {
        size_t n = fwrite(rec.data(), 1, rec.size(), file);
        if (n != rec.size()) {
          std::lock_guard<std::mutex> lock(mu);
          io_error = true;
          error = "short write to emit log";
        }
      }
      drained.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// Returns an opaque handle (heap pointer) or 0 on failure.
void* ew_open(const char* path) {
  crc32_init();
  FILE* f = fopen(path, "ab");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->file = f;
  w->thread = std::thread([w] { w->run(); });
  return w;
}

// Enqueue one framed record. Returns 0 on success, -1 on error.
// Blocks only if the queue exceeds the backpressure cap (disk is the
// bottleneck at that point anyway).
int ew_write(void* handle, const uint8_t* payload, uint64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w) return -1;
  std::vector<uint8_t> rec(16 + len);
  uint32_t magic = kMagic;
  uint32_t crc = crc32(payload, len);
  std::memcpy(rec.data(), &magic, 4);
  std::memcpy(rec.data() + 4, &crc, 4);
  std::memcpy(rec.data() + 8, &len, 8);
  std::memcpy(rec.data() + 16, payload, len);
  {
    std::unique_lock<std::mutex> lock(w->mu);
    if (w->io_error) return -1;
    w->drained.wait(lock, [&] {
      return w->queued_bytes + rec.size() <= kMaxQueueBytes || w->io_error;
    });
    if (w->io_error) return -1;
    w->queued_bytes += rec.size();
    w->queue.push_back(std::move(rec));
  }
  w->cv.notify_one();
  return 0;
}

// Block until the queue is drained and the OS buffer flushed.
int ew_flush(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w) return -1;
  {
    std::unique_lock<std::mutex> lock(w->mu);
    w->drained.wait(lock, [&] { return w->queue.empty() || w->io_error; });
    if (w->io_error) return -1;
  }
  return fflush(w->file) == 0 ? 0 : -1;
}

// Flush, stop the thread, close the file, free the handle.
int ew_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  if (!w) return -1;
  {
    std::lock_guard<std::mutex> lock(w->mu);
    w->stop = true;
  }
  w->cv.notify_all();
  w->thread.join();
  int rc = 0;
  if (w->io_error) rc = -1;
  if (fclose(w->file) != 0) rc = -1;
  delete w;
  return rc;
}

// Last error message (empty if none). Valid until the next call.
const char* ew_error(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  static thread_local std::string out;
  if (!w) return "null handle";
  std::lock_guard<std::mutex> lock(w->mu);
  out = w->error;
  return out.c_str();
}

}  // extern "C"
