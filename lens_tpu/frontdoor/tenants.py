"""Multi-tenant admission policy: who gets the next lane, and when.

The front door's job split (docs/serving.md, "Front door") follows the
Podracer/Sebulba host-vs-device discipline one level up: the serve
scheduler owns DEVICE policy (lane packing, windows, priority classes
inside its bounded queue), and this module owns TENANT policy — which
client's request is handed to the server next, and which requests are
refused before they cost anything. Everything here is plain Python
over plain data, deliberately jax-free and HTTP-free, so fairness is
unit-testable with a fake clock and no sockets.

Three mechanisms, composable per tenant (``tenants.json``):

- **Weighted deficit round robin** (:class:`TenantScheduler`): queued
  requests wait in per-(tenant, class) FIFOs; ``pop()`` serves the
  ``interactive`` class strictly ahead of ``batch`` and, within a
  class, cycles tenants crediting ``weight`` deficit per visit — a
  tenant flooding its own queue cannot push another tenant's share
  below ``weight / total_weight`` of admissions, which is the
  starvation-freedom bound tests/test_frontdoor.py pins.
- **Token-bucket rate limits** (:class:`TokenBucket`): ``rate``
  requests/second with ``burst`` capacity; an empty bucket yields the
  seconds until the next token — the HTTP 429 ``Retry-After``.
- **In-flight quotas** (``max_inflight``): a hard cap on one tenant's
  queued + running requests, the memory/lane-hoarding bound.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, fields
from typing import Any, Deque, Dict, List, Mapping, Optional, Tuple

from lens_tpu.serve.batcher import BATCH, PRIORITIES

#: Keys a tenants.json tenant entry may carry.
_TENANT_KEYS = {
    "name", "api_key", "weight", "rate", "burst", "max_inflight",
    "queue_depth", "default_priority",
}


class TenantQueueFull(Exception):
    """A tenant's front-door queue is at depth: retry after
    ``retry_after`` seconds (maps to HTTP 429 + ``Retry-After``)."""

    def __init__(self, tenant: str, depth: int, retry_after: float):
        self.tenant = tenant
        self.depth = int(depth)
        self.retry_after = float(retry_after)
        super().__init__(
            f"tenant {tenant!r} queue full ({depth} waiting); retry "
            f"in ~{self.retry_after:.2f}s"
        )


@dataclass
class TenantConfig:
    """One tenant's policy knobs (all enforcement lives in
    :class:`TenantScheduler` / the front door).

    weight:
        WDRR share (> 0). With tenants A (2.0) and B (1.0) both
        backlogged, A is admitted twice per B's once.
    rate / burst:
        Token-bucket submit rate limit: ``rate`` requests/second
        sustained, ``burst`` tokens of headroom (default
        ``max(rate, 1)``). ``None`` rate = unlimited.
    max_inflight:
        Cap on the tenant's queued-at-front-door + running requests;
        a submit past it is throttled (429). ``None`` = unlimited.
    queue_depth:
        Bound on the tenant's front-door queues (both classes
        combined); a submit past it is rejected (429 + Retry-After
        from the server's occupancy hint).
    default_priority:
        Admission class for requests that do not name one.
    api_key:
        Shared secret identifying the tenant (``Authorization:
        Bearer`` / ``X-API-Key``). ``None``: the tenant is OPEN — any
        client may claim it by name via ``X-Tenant``.
    """

    name: str
    api_key: Optional[str] = None
    weight: float = 1.0
    rate: Optional[float] = None
    burst: Optional[float] = None
    max_inflight: Optional[int] = None
    queue_depth: int = 256
    default_priority: str = BATCH

    def __post_init__(self) -> None:
        if not self.name or not isinstance(self.name, str):
            raise ValueError(f"tenant name must be a non-empty string, "
                             f"got {self.name!r}")
        if not float(self.weight) > 0:
            raise ValueError(
                f"tenant {self.name!r}: weight={self.weight} must be > 0"
            )
        if self.rate is not None and not float(self.rate) > 0:
            raise ValueError(
                f"tenant {self.name!r}: rate={self.rate} must be > 0 "
                f"(omit for unlimited)"
            )
        if self.burst is not None and not float(self.burst) >= 1:
            raise ValueError(
                f"tenant {self.name!r}: burst={self.burst} must be >= 1"
            )
        if self.max_inflight is not None and int(self.max_inflight) < 1:
            raise ValueError(
                f"tenant {self.name!r}: max_inflight="
                f"{self.max_inflight} must be >= 1"
            )
        if int(self.queue_depth) < 1:
            raise ValueError(
                f"tenant {self.name!r}: queue_depth={self.queue_depth} "
                f"must be >= 1"
            )
        if self.default_priority not in PRIORITIES:
            raise ValueError(
                f"tenant {self.name!r}: unknown default_priority "
                f"{self.default_priority!r}; known: "
                f"{', '.join(PRIORITIES)}"
            )

    @classmethod
    def from_mapping(cls, entry: Mapping[str, Any]) -> "TenantConfig":
        unknown = set(entry) - _TENANT_KEYS
        if unknown:
            raise ValueError(
                f"tenant entry {entry.get('name', '?')!r}: unknown "
                f"keys {sorted(unknown)}; known: {sorted(_TENANT_KEYS)}"
            )
        if "name" not in entry:
            raise ValueError(f"tenant entry needs a 'name': {entry!r}")
        kwargs = {f.name: entry[f.name] for f in fields(cls)
                  if f.name in entry}
        return cls(**kwargs)


def load_tenants(spec: Any) -> Dict[str, TenantConfig]:
    """Tenant table from the ``tenants.json`` form: a path, an inline
    JSON string (starts with ``{`` or ``[`` — the CLI's ``--tenants``
    accepts both), a list of tenant entries, or ``{"tenants": [...]}``.
    Returns ``{name: TenantConfig}``; duplicate names and duplicate
    api_keys raise."""
    if isinstance(spec, str):
        if spec.lstrip().startswith(("{", "[")):
            spec = json.loads(spec)
        else:
            with open(spec) as f:
                spec = json.load(f)
    if isinstance(spec, Mapping):
        unknown = set(spec) - {"tenants"}
        if unknown:
            raise ValueError(
                f"unknown tenants-spec keys {sorted(unknown)}; known: "
                f"tenants"
            )
        spec = spec.get("tenants") or []
    if not isinstance(spec, (list, tuple)):
        raise ValueError(
            f"tenants spec must be a list of tenant entries (or "
            f"{{'tenants': [...]}}), got {type(spec).__name__}"
        )
    out: Dict[str, TenantConfig] = {}
    keys: Dict[str, str] = {}
    for entry in spec:
        cfg = (
            entry if isinstance(entry, TenantConfig)
            else TenantConfig.from_mapping(entry)
        )
        if cfg.name in out:
            raise ValueError(f"duplicate tenant name {cfg.name!r}")
        if cfg.api_key is not None:
            if cfg.api_key in keys:
                raise ValueError(
                    f"tenants {keys[cfg.api_key]!r} and {cfg.name!r} "
                    f"share an api_key"
                )
            keys[cfg.api_key] = cfg.name
        out[cfg.name] = cfg
    if not out:
        raise ValueError("tenants spec names no tenants")
    return out


class TokenBucket:
    """Classic token bucket, lazily refilled at ``take`` time.

    ``take()`` returns 0.0 when a token was granted, else the seconds
    until one becomes available (the Retry-After hint). ``clock`` is
    injectable so rate-limit tests need no real sleeping.
    """

    def __init__(
        self, rate: float, burst: Optional[float] = None, clock=None
    ):
        if not rate > 0:
            raise ValueError(f"rate={rate} must be > 0")
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(
            self.rate, 1.0
        )
        if self.burst < 1:
            raise ValueError(f"burst={self.burst} must be >= 1")
        self._clock = clock if clock is not None else time.monotonic
        self._tokens = self.burst
        self._stamp = self._clock()

    def take(self) -> float:
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._stamp) * self.rate
        )
        self._stamp = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return 0.0
        return (1.0 - self._tokens) / self.rate


@dataclass
class Entry:
    """One request waiting at the front door: everything the pump
    needs to submit it to the server under its reserved id."""

    rid: str
    tenant: str
    priority: str
    request: Any  # a validated ScenarioRequest
    received_at: float = 0.0


class _Ring:
    """One priority class's DRR ring: tenant order is registration
    order (deterministic), ``next_tenant`` credits ``weight`` deficit
    per visit and serves a tenant while its deficit lasts."""

    def __init__(self) -> None:
        self.order: List[str] = []
        self.deficit: Dict[str, float] = {}
        self.idx = 0

    def add(self, tenant: str) -> None:
        if tenant not in self.deficit:
            self.order.append(tenant)
            self.deficit[tenant] = 0.0


class TenantScheduler:
    """Per-tenant weighted deficit-round-robin queues in front of the
    serve scheduler's bounded FIFO.

    NOT thread-safe by itself — the front door serializes access under
    its server lock (one lock for tenant policy + server calls keeps
    the admission order a single serialized history, which is what
    makes fairness testable).
    """

    def __init__(
        self,
        tenants: Mapping[str, TenantConfig],
        clock=None,
    ):
        self.tenants = dict(tenants)
        self._clock = clock if clock is not None else time.monotonic
        self._queues: Dict[Tuple[str, str], Deque[Entry]] = {}
        # an entry the server refused with QueueFull after it was
        # popped: it goes out FIRST on the next pop (its WDRR turn was
        # already spent on it)
        self._head: Optional[Entry] = None
        self._rings = {cls: _Ring() for cls in PRIORITIES}
        self._buckets: Dict[str, TokenBucket] = {}
        self.inflight: Dict[str, int] = {}
        for name, cfg in self.tenants.items():
            for cls in PRIORITIES:
                self._queues[(name, cls)] = deque()
                self._rings[cls].add(name)
            if cfg.rate is not None:
                self._buckets[name] = TokenBucket(
                    cfg.rate, cfg.burst, clock=self._clock
                )
            self.inflight[name] = 0

    # -- ingress checks (the front door's 429 sources) -----------------------

    def queued(self, tenant: Optional[str] = None) -> int:
        head = (
            1 if self._head is not None
            and (tenant is None or self._head.tenant == tenant)
            else 0
        )
        if tenant is not None:
            return head + sum(
                len(self._queues[(tenant, cls)]) for cls in PRIORITIES
            )
        return head + sum(len(q) for q in self._queues.values())

    def throttle(self, tenant: str) -> Tuple[Optional[str], float]:
        """Rate/quota check for one incoming request: ``(None, 0.0)``
        to proceed, else ``(reason, retry_after)`` — the front door
        turns a reason into a tenant-scoped 429. Consumes a token on
        success (the request WILL be queued)."""
        cfg = self.tenants[tenant]
        if cfg.max_inflight is not None:
            busy = self.queued(tenant) + self.inflight[tenant]
            if busy >= cfg.max_inflight:
                return (
                    f"tenant {tenant!r} is at its in-flight quota "
                    f"({busy}/{cfg.max_inflight} requests queued or "
                    f"running)",
                    1.0,
                )
        bucket = self._buckets.get(tenant)
        if bucket is not None:
            wait = bucket.take()
            if wait > 0:
                return (
                    f"tenant {tenant!r} is over its rate limit "
                    f"({cfg.rate}/s)",
                    wait,
                )
        return None, 0.0

    def push(self, entry: Entry, retry_after: float = 1.0) -> None:
        """Queue one admitted-at-ingress request; raises
        :class:`TenantQueueFull` past the tenant's depth bound."""
        cfg = self.tenants[entry.tenant]
        if self.queued(entry.tenant) >= cfg.queue_depth:
            raise TenantQueueFull(
                entry.tenant, self.queued(entry.tenant), retry_after
            )
        self._queues[(entry.tenant, entry.priority)].append(entry)

    # -- egress (the pump's WDRR pop) ----------------------------------------

    def pop(self) -> Optional[Entry]:
        """The next request to hand the serve scheduler: a refused
        head entry first, then the interactive class strictly ahead
        of batch; within a class, weighted deficit round robin over
        tenants (FIFO per tenant). Returns None when nothing is
        queued."""
        if self._head is not None:
            entry, self._head = self._head, None
            return entry
        for cls in PRIORITIES:
            entry = self._pop_ring(cls)
            if entry is not None:
                return entry
        return None

    def _pop_ring(self, cls: str) -> Optional[Entry]:
        ring = self._rings[cls]
        active = [
            t for t in ring.order if self._queues[(t, cls)]
        ]
        if not active:
            # idle class: deficits reset so a later burst starts fair
            # (standard DRR — credit must not accrue while empty)
            for t in ring.order:
                ring.deficit[t] = 0.0
            return None
        # bounded scan: each full pass over the active tenants credits
        # every deficit by its weight, so within ceil(1/min_weight)
        # passes someone can afford a request. A tenant's turn lasts
        # while its deficit covers another request (weight 2 serves
        # two per visit); the pointer advances the moment its deficit
        # breaks, so no tenant can be revisited before the others.
        min_w = min(self.tenants[t].weight for t in active)
        for _ in range(2 * len(active) * (int(1.0 / min_w) + 2)):
            t = active[ring.idx % len(active)]
            if not self._queues[(t, cls)]:
                ring.deficit[t] = 0.0
                ring.idx += 1
                continue
            if ring.deficit[t] >= 1.0:
                ring.deficit[t] -= 1.0
                if ring.deficit[t] < 1.0:
                    ring.idx += 1  # turn exhausted AFTER this serve
                return self._queues[(t, cls)].popleft()
            ring.deficit[t] += self.tenants[t].weight
            if ring.deficit[t] < 1.0:
                ring.idx += 1
        # unreachable for weights > 0; be loud rather than spin
        raise RuntimeError("WDRR failed to converge (weights broken?)")

    def push_front(self, entry: Entry) -> None:
        """Return a popped entry to the scheduler's head slot (the
        server refused it with QueueFull): it keeps its turn — the
        next pop hands it out again before any ring is consulted."""
        if self._head is not None:
            raise RuntimeError(
                "push_front called with a head entry already parked "
                "(the pump must re-pop before refusing again)"
            )
        self._head = entry

    def cancel(self, rid: str) -> Optional[Entry]:
        """Remove a still-queued request by id (front-door cancel)."""
        if self._head is not None and self._head.rid == rid:
            entry, self._head = self._head, None
            return entry
        for q in self._queues.values():
            for entry in q:
                if entry.rid == rid:
                    q.remove(entry)
                    return entry
        return None

    # -- inflight accounting -------------------------------------------------

    def note_submitted(self, tenant: str) -> None:
        self.inflight[tenant] += 1

    def note_finished(self, tenant: str) -> None:
        self.inflight[tenant] = max(0, self.inflight[tenant] - 1)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Live per-tenant queue/inflight gauges (the /healthz body)."""
        return {
            name: {
                "queued": self.queued(name),
                "inflight": self.inflight[name],
                "weight": cfg.weight,
                "rate": cfg.rate,
                "max_inflight": cfg.max_inflight,
            }
            for name, cfg in self.tenants.items()
        }
