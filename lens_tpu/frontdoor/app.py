"""The async HTTP front door over a resident :class:`SimServer`.

ROADMAP item 5's last gap: after rounds 8-14 the serving core is
production-shaped (continuous batching, prefix caching, WAL recovery,
device failover, tracing) but only reachable in-process. This module
is the thin host-side layer that makes it reachable from a socket —
and the FIRST layer where policy is about WHO is asking (tenants,
priorities, rate limits), which is exactly why it sits outside the
device-side scheduler (the Podracer split: all tenancy policy is cheap
host Python; the compiled lane programs never learn HTTP exists).

Stdlib only by design: an asyncio HTTP/1.1 server (keep-alive,
chunked responses) written against ``asyncio.start_server`` — no new
dependency for the repo, and nothing the container doesn't have.

Surface (docs/serving.md, "Front door"):

==========================================  ================================
``POST   /v1/requests``                     submit; 202 ``{"rid": ...}``
``GET    /v1/requests/{rid}``               status + timing-table row
``GET    /v1/requests/{rid}/stream``        SSE record stream (chunked)
``DELETE /v1/requests/{rid}``               cancel (queued or running)
``GET    /healthz``                         liveness: occupancy, queue,
                                            quarantined devices, tenants
``GET    /v1/status``                       full metrics snapshot
``GET    /metrics``                         Prometheus text exposition
==========================================  ================================

Error mapping is part of the contract: malformed request JSON is a 400
whose body carries the machine-readable field ``path`` from
:class:`~lens_tpu.serve.batcher.RequestValidationError`; backpressure
(tenant queue full) and throttling (rate limit, in-flight quota) are
429 with a ``Retry-After`` header derived from the server's
occupancy-based hint; a draining server answers submits with 503 +
``Retry-After``; unknown/foreign rids are 404 (a tenant can never
probe another tenant's ids).

Threading model: ONE scheduler thread owns the `SimServer` hot loop
(tenant-scheduler pump, then ``tick()``) under one lock; the asyncio
loop runs in a second thread and reaches the server through a small
executor that takes the same lock — so every admission decision is a
single serialized history (what makes fairness testable), while SSE
streams read result logs lock-free via the tail-frames contract.
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

from lens_tpu.serve.batcher import (
    CANCELLED,
    DONE,
    FAILED,
    QueueFull,
    RequestValidationError,
    TIMEOUT,
)
from lens_tpu.serve.metrics import request_timing_row
from lens_tpu.frontdoor.auth import AuthError, Authenticator
from lens_tpu.frontdoor.streams import record_events, sse_event
from lens_tpu.frontdoor.tenants import (
    Entry,
    TenantConfig,
    TenantQueueFull,
    TenantScheduler,
    load_tenants,
)

#: Span-trace track for front-door events (docs/observability.md).
FRONTDOOR_TRACK = "frontdoor"

_TERMINAL = (DONE, TIMEOUT, CANCELLED, FAILED)

_STATUS_TEXT = {
    200: "OK", 202: "Accepted", 400: "Bad Request",
    401: "Unauthorized", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout",
    413: "Payload Too Large", 429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}


class _HttpRequest:
    __slots__ = ("method", "path", "headers", "body")

    def __init__(self, method: str, path: str,
                 headers: Dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.headers = headers
        self.body = body


class FrontDoor:
    """Serve a :class:`SimServer` over HTTP with multi-tenant
    fair-share admission.

    Parameters
    ----------
    server:
        The resident ``SimServer``. Must use ``sink="log"`` — result
        streaming reads the per-request ``.lens`` logs.
    tenants:
        ``None`` (open single-tenant mode: one implicit unlimited
        ``default`` tenant), a path to a ``tenants.json``, a list of
        tenant entries, or a ``{name: TenantConfig}`` mapping — see
        :mod:`lens_tpu.frontdoor.tenants`.
    host / port:
        Bind address; port 0 picks a free port (``.port`` reports the
        bound one after :meth:`start`).
    own_server:
        When True, :meth:`drain`/:meth:`close` also close the
        ``SimServer`` (the CLI's mode; in-process callers usually keep
        ownership).
    idle_sleep_s:
        Scheduler-thread sleep when the server is fully idle (keeps an
        idle front door near-zero CPU without adding admission latency
        under load).
    max_body_bytes:
        Bound on a request body (413 past it).
    warm:
        Speculative prefix warming from observed traffic
        (docs/serving.md, "Tiered snapshots & speculative warming"):
        the door tracks each tenant's request PREFIX shapes, and a
        shape seen more than once is ruled popular — whenever the
        server goes idle, popular prefixes are handed to
        ``SimServer.prewarm`` so a demoted snapshot is promoted back
        to the device tier (or a missing one recomputed in an idle
        lane) BEFORE the next repeat arrives. Strictly scavenging:
        warm work never delays an admitted request. Default off.
    """

    #: Most-popular prefixes kept per warming pass, and the sighting
    #: count past which a shape is ruled popular.
    WARM_TOP_K = 8
    WARM_MIN_SEEN = 2

    def __init__(
        self,
        server: Any,
        tenants: Any = None,
        host: str = "127.0.0.1",
        port: int = 0,
        own_server: bool = False,
        idle_sleep_s: float = 0.002,
        max_body_bytes: int = 8 << 20,
        stream_poll_s: float = 0.02,
        warm: bool = False,
    ):
        if getattr(server, "sink", None) != "log":
            raise ValueError(
                "FrontDoor needs a SimServer with sink='log' (record "
                "streaming reads the per-request result logs)"
            )
        self.server = server
        if tenants is None:
            table: Dict[str, TenantConfig] = {
                "default": TenantConfig(name="default")
            }
        elif isinstance(tenants, Mapping) and all(
            isinstance(v, TenantConfig) for v in tenants.values()
        ):
            table = dict(tenants)
        else:
            table = load_tenants(tenants)
        self.tenants = table
        self.auth = Authenticator(table)
        self.sched = TenantScheduler(table)
        self.host = host
        self._requested_port = int(port)
        self.port: Optional[int] = None
        self.own_server = bool(own_server)
        self.idle_sleep_s = float(idle_sleep_s)
        self.max_body_bytes = int(max_body_bytes)
        self.stream_poll_s = float(stream_poll_s)
        # one lock serializes ALL SimServer access (scheduler thread's
        # pump+tick, the HTTP executor's submits/status/cancels)
        self._lock = threading.RLock()
        self._pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="frontdoor-http"
        )
        self._rid_tenant: Dict[str, str] = {}   # rid -> owning tenant
        self._inflight_rids: Dict[str, str] = {}  # submitted, not done
        # speculative warming (warm=True): per-(tenant, prefix-shape)
        # sighting counts plus the prewarm spec each shape denotes;
        # popular shapes are prewarmed at idle (_scheduler_loop)
        self.warm = bool(warm)
        self._prefix_seen: Dict[Any, int] = {}
        self._prefix_spec: Dict[Any, Dict[str, Any]] = {}
        # one warming pass per idle period, ONE prewarm per loop
        # iteration: a disk promotion is blocking I/O under the door
        # lock, so the pass is spread across iterations — an HTTP
        # request arriving mid-pass waits for at most one promotion,
        # never the whole popular list
        self._warm_plan: list = []
        self._warmed_idle = False
        self._done_at_door: Dict[str, Tuple[str, Optional[str]]] = {}
        self._draining = False
        # a fatal scheduler error (parked stream failure, watchdog):
        # the loop thread died — the door flips to draining and every
        # submit answers 503 naming the cause instead of accepting
        # work nothing will ever run
        self._sched_error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._sched_thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_thread: Optional[threading.Thread] = None
        self._http_server: Optional[asyncio.AbstractServer] = None
        self._active_streams = 0
        self._started = False
        self._closed = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FrontDoor":
        """Bind the socket, start the asyncio loop thread and the
        scheduler thread. Returns self (so ``FrontDoor(...).start()``
        composes)."""
        if self._started:
            return self
        self._started = True
        self._loop = asyncio.new_event_loop()
        ready = threading.Event()

        def run_loop() -> None:
            asyncio.set_event_loop(self._loop)
            self._loop.call_soon(ready.set)
            self._loop.run_forever()

        self._loop_thread = threading.Thread(
            target=run_loop, name="frontdoor-loop", daemon=True
        )
        self._loop_thread.start()
        ready.wait()
        fut = asyncio.run_coroutine_threadsafe(
            self._start_http(), self._loop
        )
        fut.result(timeout=10)
        self._sched_thread = threading.Thread(
            target=self._scheduler_loop, name="frontdoor-sched",
            daemon=True,
        )
        self._sched_thread.start()
        return self

    async def _start_http(self) -> None:
        self._http_server = await asyncio.start_server(
            self._handle_connection, host=self.host,
            port=self._requested_port,
            family=socket.AF_INET,
        )
        self.port = self._http_server.sockets[0].getsockname()[1]

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop accepting new work (submits answer
        503 + Retry-After), let everything queued and in flight run to
        completion, give open streams a moment to deliver their
        ``end`` events, then stop the threads (and close the server
        when ``own_server``). Returns True when fully drained within
        ``timeout`` (None = wait indefinitely); on False the caller
        decides between waiting more and a hard :meth:`close`."""
        self._draining = True
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )

        def expired() -> bool:
            return deadline is not None and time.monotonic() > deadline

        dead = False
        while not expired():
            if self._sched_error is not None or (
                self._sched_thread is not None
                and not self._sched_thread.is_alive()
            ):
                # the scheduler thread died on a fatal server error:
                # nothing will ever pump or tick again, so waiting on
                # the queues is waiting forever — close now and report
                # the drain as failed
                dead = True
                break
            with self._lock:
                busy = (
                    self.sched.queued()
                    or self._inflight_rids
                    or len(self.server.queue)
                    or any(
                        b.busy() for b in self.server.buckets.values()
                    )
                )
            if not busy and self._active_streams == 0:
                break
            time.sleep(0.02)
        drained = not expired() and not dead
        self.close()
        return drained

    def close(self) -> None:
        """Stop threads and the HTTP listener NOW (queued front-door
        entries are dropped; the server keeps whatever it already
        accepted). Idempotent; closes the ``SimServer`` only under
        ``own_server``."""
        if self._closed:
            return
        self._closed = True
        self._draining = True
        self._stop.set()
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=30)
        if self._loop is not None:
            async def shutdown() -> None:
                if self._http_server is not None:
                    self._http_server.close()
                    await self._http_server.wait_closed()
                # open keep-alive connections hold pending handler
                # tasks; cancel them so the loop stops clean
                tasks = [
                    t for t in asyncio.all_tasks()
                    if t is not asyncio.current_task()
                ]
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)

            try:
                asyncio.run_coroutine_threadsafe(
                    shutdown(), self._loop
                ).result(timeout=10)
            except Exception:
                pass
            self._loop.call_soon_threadsafe(self._loop.stop)
            if self._loop_thread is not None:
                self._loop_thread.join(timeout=10)
            if not self._loop.is_running():
                self._loop.close()
        self._pool.shutdown(wait=False)
        if self.own_server:
            self.server.close()

    def __enter__(self) -> "FrontDoor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- scheduler thread ----------------------------------------------------

    def _scheduler_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                self._sweep_inflight()
                self._pump()
                try:
                    busy = self.server.tick()
                except Exception as e:
                    # a fatal server error (parked stream failure,
                    # watchdog): stop ticking and flip the door to
                    # draining — new submits answer 503 naming this
                    # cause instead of queueing work nothing will run
                    self._sched_error = e
                    self._draining = True
                    self._stop.set()
                    raise
                waiting = self.sched.queued() or len(self.server.queue)
            if not busy and not waiting:
                if self.warm and not self._draining \
                        and not self._warmed_idle:
                    # idle: re-warm this door's popular prefixes (a
                    # demoted one promotes back to device, an evicted
                    # one re-runs in the now-idle lanes). One shape
                    # per iteration — the lock is released between
                    # promotions — and one pass per idle period:
                    # prewarm is a no-op for anything already
                    # resident, but no reason to spin on it.
                    with self._lock:
                        self._prewarm_popular_step()
                time.sleep(self.idle_sleep_s)
            else:
                self._warmed_idle = False
                self._warm_plan.clear()

    def _pump(self) -> None:
        """Move requests from the tenant scheduler into the server's
        bounded queue — the WDRR egress. Gated on free queue depth so
        the pump never bounces off ``QueueFull`` (which would count
        spurious rejects); the server queue's own bound therefore
        backpressures the TENANT queues, whose bounds backpressure the
        clients as 429s."""
        while len(self.server.queue) < self.server.queue.max_depth:
            entry = self.sched.pop()
            if entry is None:
                return
            try:
                self.server.submit(entry.request, rid=entry.rid)
            except QueueFull:
                # unreachable while the depth gate above holds, but a
                # refused entry must never lose its place: re-park it
                # at the head and try again next pump
                self.sched.push_front(entry)
                return
            except Exception as e:
                # validated at ingress, so this is rare (e.g. every
                # device quarantined since) — record a front-door
                # terminal so the client's status poll sees the cause
                self._bounded_put(
                    self._done_at_door, entry.rid,
                    (FAILED, f"{type(e).__name__}: {e}"),
                    self.DOOR_TERMINAL_RETENTION,
                )
                continue
            self.sched.note_submitted(entry.tenant)
            self._inflight_rids[entry.rid] = entry.tenant
            if self.warm:
                self._note_prefix(entry.tenant, entry.request)
            if self.server.trace:
                self.server.trace.emit_span(
                    "frontdoor.request", entry.received_at,
                    time.perf_counter(), track=FRONTDOOR_TRACK,
                    aid=entry.rid, rid=entry.rid,
                    tenant=entry.tenant, priority=entry.priority,
                )

    def _note_prefix(self, tenant: str, request: Any) -> None:
        """Record one accepted request's prefix shape against its
        tenant — repeated shapes are the door's traffic oracle (an
        HTTP client re-running what-if forks off one scenario submits
        the same prefix block over and over)."""
        spec = request.prefix_spec()
        if spec is None:
            return
        shape = (
            tenant,
            json.dumps(spec, sort_keys=True, default=str),
        )
        self._prefix_seen[shape] = self._prefix_seen.get(shape, 0) + 1
        self._prefix_spec[shape] = spec
        if len(self._prefix_seen) > self.DOOR_TERMINAL_RETENTION:
            # evict the LEAST-SEEN shapes: insertion order would purge
            # the oldest entries, which are exactly the long-lived
            # popular prefixes the oracle exists to remember
            for _, old in sorted(
                (seen, shape)
                for shape, seen in self._prefix_seen.items()
            )[:1000]:
                del self._prefix_seen[old]
                self._prefix_spec.pop(old, None)

    def _prewarm_popular_step(self) -> None:
        """Hand ONE popular prefix to ``SimServer.prewarm`` per call
        (caller holds the scheduler lock; a step is at most one disk
        promotion). The first step of an idle period plans the pass —
        the top popular shapes by sighting count — and the pass marks
        itself done when the plan drains. Advisory end to end: a
        validation error just drops the shape from the table."""
        if not self._warm_plan:
            self._warm_plan = [
                shape
                for _, shape in sorted(
                    (
                        (seen, shape)
                        for shape, seen in self._prefix_seen.items()
                        if seen >= self.WARM_MIN_SEEN
                    ),
                    reverse=True,
                )[: self.WARM_TOP_K]
            ]
            if not self._warm_plan:
                self._warmed_idle = True
                return
        shape = self._warm_plan.pop(0)
        try:
            self.server.prewarm(self._prefix_spec[shape])
        except (ValueError, KeyError):
            self._prefix_seen.pop(shape, None)
            self._prefix_spec.pop(shape, None)
        if not self._warm_plan:
            self._warmed_idle = True

    #: Retention bounds for the per-request maps a long-running door
    #: would otherwise grow forever (one entry per request EVER
    #: accepted). Past the bound the OLDEST tenth is evicted (dicts
    #: iterate in insertion order) — an evicted rid reads as 404,
    #: which is the documented retention contract for ancient ids.
    RID_RETENTION = 200_000
    DOOR_TERMINAL_RETENTION = 20_000

    @staticmethod
    def _bounded_put(table: Dict, key, value, bound: int) -> None:
        table[key] = value
        if len(table) > bound:
            for old in list(table)[: max(bound // 10, 1)]:
                del table[old]

    def _sweep_inflight(self) -> None:
        if not self._inflight_rids:
            return
        done = [
            (rid, tenant)
            for rid, tenant in self._inflight_rids.items()
            if getattr(
                self.server.tickets.get(rid), "status", FAILED
            ) in _TERMINAL
        ]
        for rid, tenant in done:
            self.sched.note_finished(tenant)
            del self._inflight_rids[rid]

    # -- HTTP plumbing -------------------------------------------------------

    async def _handle_connection(self, reader, writer) -> None:
        try:
            while True:
                request = await self._read_request(reader, writer)
                if request is None:
                    return
                keep = await self._dispatch(request, writer)
                if not keep:
                    return
        except (
            ConnectionResetError, BrokenPipeError, asyncio.TimeoutError
        ):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(
        self, reader, writer
    ) -> Optional[_HttpRequest]:
        try:
            line = await reader.readline()
        except Exception:
            return None
        if not line:
            return None
        parts = line.decode("latin-1").strip().split()
        if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
            await self._respond(
                writer, 400, {"error": "malformed request line"},
                keep_alive=False,
            )
            return None
        method, target, _version = parts
        headers: Dict[str, str] = {}
        while True:
            line = await reader.readline()
            if not line or line in (b"\r\n", b"\n"):
                break
            name, _, value = line.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
            if len(headers) > 100:
                await self._respond(
                    writer, 400, {"error": "too many headers"},
                    keep_alive=False,
                )
                return None
        body = b""
        length = headers.get("content-length")
        if length is not None:
            try:
                n = int(length)
            except ValueError:
                await self._respond(
                    writer, 400,
                    {"error": "malformed Content-Length"},
                    keep_alive=False,
                )
                return None
            if n > self.max_body_bytes:
                await self._respond(
                    writer, 413,
                    {"error": f"body exceeds {self.max_body_bytes} "
                              f"bytes"},
                    keep_alive=False,
                )
                return None
            if headers.get("expect", "").lower() == "100-continue":
                writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
                await writer.drain()
            body = await reader.readexactly(n)
        path = target.split("?", 1)[0]
        return _HttpRequest(method.upper(), path, headers, body)

    async def _respond(
        self,
        writer,
        status: int,
        payload: Any,
        keep_alive: bool = True,
        extra_headers: Optional[Mapping[str, str]] = None,
        content_type: str = "application/json",
    ) -> None:
        if isinstance(payload, (dict, list)):
            body = (json.dumps(payload) + "\n").encode()
        elif isinstance(payload, str):
            body = payload.encode()
        else:
            body = bytes(payload)
        head = [
            f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'Unknown')}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(body)}",
            f"Connection: {'keep-alive' if keep_alive else 'close'}",
        ]
        for name, value in (extra_headers or {}).items():
            head.append(f"{name}: {value}")
        writer.write(
            ("\r\n".join(head) + "\r\n\r\n").encode() + body
        )
        await writer.drain()

    async def _locked(self, fn: Callable[[], Any]) -> Any:
        """Run a server-touching callable on the executor under the
        admission lock (never block the event loop on the lock)."""

        def call():
            with self._lock:
                return fn()

        return await asyncio.get_running_loop().run_in_executor(
            self._pool, call
        )

    # -- routing -------------------------------------------------------------

    async def _dispatch(self, req: _HttpRequest, writer) -> bool:
        try:
            if req.path == "/healthz" and req.method == "GET":
                return await self._get_healthz(req, writer)
            if req.path == "/metrics" and req.method == "GET":
                return await self._get_metrics(req, writer)
            if req.path == "/v1/status" and req.method == "GET":
                return await self._get_status(req, writer)
            if req.path == "/v1/requests" and req.method == "POST":
                return await self._post_request(req, writer)
            if req.path.startswith("/v1/requests/"):
                rest = req.path[len("/v1/requests/"):]
                if rest.endswith("/stream") and req.method == "GET":
                    return await self._get_stream(
                        req, writer, rest[: -len("/stream")].rstrip("/")
                    )
                rid = rest.rstrip("/")
                if "/" not in rid:
                    if req.method == "GET":
                        return await self._get_request(req, writer, rid)
                    if req.method == "DELETE":
                        return await self._delete_request(
                            req, writer, rid
                        )
                    await self._respond(
                        writer, 405,
                        {"error": f"{req.method} not allowed here"},
                    )
                    return True
            await self._respond(
                writer, 404, {"error": f"no route {req.method} "
                                       f"{req.path}"},
            )
            return True
        except AuthError as e:
            await self._respond(
                writer, e.status, {"error": str(e)},
            )
            return True
        except (
            ConnectionResetError, BrokenPipeError
        ):
            return False
        except Exception as e:
            try:
                await self._respond(
                    writer, 500,
                    {"error": f"{type(e).__name__}: {e}"},
                    keep_alive=False,
                )
            except Exception:
                pass
            return False

    def _owner_or_none(
        self, tenant: TenantConfig, rid: str
    ) -> Optional[str]:
        owner = self._rid_tenant.get(rid)
        if owner is None or owner != tenant.name:
            return None
        return owner

    # -- handlers ------------------------------------------------------------

    async def _post_request(self, req: _HttpRequest, writer) -> bool:
        tenant = self.auth.resolve(req.headers)
        t_recv = time.perf_counter()
        try:
            mapping = json.loads(req.body.decode() or "null")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            await self._respond(
                writer, 400,
                {"error": f"body is not valid JSON: {e}", "path": None},
            )
            return True
        if not isinstance(mapping, dict):
            await self._respond(
                writer, 400,
                {"error": "body must be a JSON request object",
                 "path": None},
            )
            return True
        claimed = mapping.get("tenant")
        if claimed is not None and claimed != tenant.name:
            await self._respond(
                writer, 403,
                {"error": f"authenticated as tenant {tenant.name!r}; "
                          f"request names {claimed!r}",
                 "path": "tenant"},
            )
            return True

        def ingress():
            body = dict(mapping)
            body["tenant"] = tenant.name
            body.setdefault("priority", tenant.default_priority)
            if "composite" not in body and len(
                self.server.buckets
            ) == 1:
                body["composite"] = next(iter(self.server.buckets))
            try:
                request = self.server.validate(body)
            except RequestValidationError as e:
                return 400, {"error": str(e), "path": e.path}, {}
            except ValueError as e:
                return 400, {"error": str(e), "path": None}, {}
            if self._draining:
                hint = max(self.server.retry_after_hint(), 1.0)
                cause = (
                    f"server is down: {type(self._sched_error).__name__}"
                    f": {self._sched_error}"
                    if self._sched_error is not None
                    else "server is draining; not accepting new "
                         "requests"
                )
                return 503, {
                    "error": cause, "tenant": tenant.name,
                }, {"Retry-After": f"{hint:.3f}"}
            # queue-depth check BEFORE the token bucket: a rejected
            # submit must not burn a rate-limit token the tenant never
            # got a queue slot for
            if self.sched.queued(tenant.name) >= tenant.queue_depth:
                self.server._metrics.tenant_inc(
                    tenant.name, "rejected"
                )
                hint = max(self.server.retry_after_hint(), 0.05)
                return 429, {
                    "error": f"tenant {tenant.name!r} queue full "
                             f"({tenant.queue_depth} waiting)",
                    "tenant": tenant.name,
                }, {"Retry-After": f"{hint:.3f}"}
            reason, wait = self.sched.throttle(tenant.name)
            if reason is not None:
                self.server._metrics.tenant_inc(
                    tenant.name, "throttled"
                )
                return 429, {
                    "error": reason, "tenant": tenant.name,
                }, {"Retry-After": f"{max(wait, 0.001):.3f}"}
            rid = self.server.reserve_id()
            entry = Entry(
                rid=rid,
                tenant=tenant.name,
                priority=str(body["priority"]),
                request=request,
                received_at=t_recv,
            )
            try:
                self.sched.push(
                    entry,
                    retry_after=max(
                        self.server.retry_after_hint(), 0.05
                    ),
                )
            except TenantQueueFull as e:
                self.server._metrics.tenant_inc(
                    tenant.name, "rejected"
                )
                return 429, {
                    "error": str(e), "tenant": tenant.name,
                }, {"Retry-After": f"{e.retry_after:.3f}"}
            self._bounded_put(
                self._rid_tenant, rid, tenant.name,
                self.RID_RETENTION,
            )
            return 202, {
                "rid": rid,
                "status": "queued",
                "tenant": tenant.name,
                "priority": entry.priority,
            }, {}

        status, payload, headers = await self._locked(ingress)
        await self._respond(
            writer, status, payload, extra_headers=headers
        )
        return True

    async def _get_request(
        self, req: _HttpRequest, writer, rid: str
    ) -> bool:
        tenant = self.auth.resolve(req.headers)
        if self._owner_or_none(tenant, rid) is None:
            await self._respond(
                writer, 404, {"error": f"unknown request {rid!r}"}
            )
            return True

        def fetch():
            t = self.server.tickets.get(rid)
            if t is not None:
                out = self.server.status(rid)
                out["tenant"] = tenant.name
                out["priority"] = t.request.priority
                if not out.get("timing"):
                    out["timing"] = request_timing_row(
                        t, self.server._metrics._t0
                    )
                return out
            if rid in self._done_at_door:
                status, error = self._done_at_door[rid]
                return {
                    "request_id": rid, "status": status,
                    "error": error, "tenant": tenant.name,
                }
            return {
                "request_id": rid, "status": "queued",
                "stage": "frontdoor", "tenant": tenant.name,
            }

        await self._respond(writer, 200, await self._locked(fetch))
        return True

    async def _delete_request(
        self, req: _HttpRequest, writer, rid: str
    ) -> bool:
        tenant = self.auth.resolve(req.headers)
        if self._owner_or_none(tenant, rid) is None:
            await self._respond(
                writer, 404, {"error": f"unknown request {rid!r}"}
            )
            return True

        def cancel():
            entry = self.sched.cancel(rid)
            if entry is not None:
                self._bounded_put(
                    self._done_at_door, rid, (CANCELLED, None),
                    self.DOOR_TERMINAL_RETENTION,
                )
                return {"request_id": rid, "status": CANCELLED}
            if rid in self.server.tickets:
                return {
                    "request_id": rid,
                    "status": self.server.cancel(rid),
                }
            status, error = self._done_at_door.get(
                rid, (CANCELLED, None)
            )
            return {"request_id": rid, "status": status,
                    "error": error}

        await self._respond(writer, 200, await self._locked(cancel))
        return True

    async def _get_stream(
        self, req: _HttpRequest, writer, rid: str
    ) -> bool:
        tenant = self.auth.resolve(req.headers)
        if self._owner_or_none(tenant, rid) is None:
            await self._respond(
                writer, 404, {"error": f"unknown request {rid!r}"}
            )
            return True

        def state() -> Dict[str, Any]:
            # lock-free scalar reads (GIL-atomic): one poll may see a
            # one-tick-stale status, never a torn one
            t = self.server.tickets.get(rid)
            if t is None:
                if rid in self._done_at_door:
                    status, error = self._done_at_door[rid]
                    return {
                        "rid": rid, "status": status, "error": error,
                        "terminal": True, "streamed": True,
                        "path": None,
                    }
                return {
                    "rid": rid, "status": "queued", "terminal": False,
                    "streamed": False, "path": None, "error": None,
                }
            return {
                "rid": rid,
                "status": t.status,
                "terminal": t.status in _TERMINAL,
                "streamed": t.streamed_at is not None,
                "path": t.result_path,
                "error": t.error,
                # bumps when a device quarantine displaces the
                # request and its sink restarts: the stream resets
                "epoch": t.requeues,
            }

        head = (
            "HTTP/1.1 200 OK\r\n"
            "Content-Type: text/event-stream\r\n"
            "Cache-Control: no-store\r\n"
            "Transfer-Encoding: chunked\r\n"
            "Connection: keep-alive\r\n\r\n"
        )
        writer.write(head.encode())
        await writer.drain()
        metrics = self.server._metrics
        t0 = time.perf_counter()
        self._active_streams += 1
        try:
            try:
                async for chunk in record_events(
                    state,
                    poll_s=self.stream_poll_s,
                    on_bytes=lambda n: metrics.tenant_inc(
                        tenant.name, "streamed_bytes", n
                    ),
                ):
                    writer.write(
                        f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n"
                    )
                    await writer.drain()
            except (ConnectionResetError, BrokenPipeError):
                return False  # client went away mid-stream
            except Exception as e:
                # the response HEAD is already on the wire: a 500 now
                # would land inside the chunked body and corrupt the
                # framing. Terminate the stream in-band instead: one
                # SSE error event, then the terminal chunk, then close
                # the connection (no keep-alive after a torn stream).
                chunk = sse_event(
                    "error",
                    json.dumps({"error": f"{type(e).__name__}: {e}"}),
                )
                try:
                    writer.write(
                        f"{len(chunk):x}\r\n".encode() + chunk
                        + b"\r\n0\r\n\r\n"
                    )
                    await writer.drain()
                except Exception:
                    pass
                return False
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        finally:
            self._active_streams -= 1
            if self.server.trace:
                self.server.trace.emit_span(
                    "frontdoor.stream", t0, time.perf_counter(),
                    track=FRONTDOOR_TRACK, aid=f"{rid}/stream",
                    rid=rid, tenant=tenant.name,
                )
        return True

    async def _get_healthz(self, req: _HttpRequest, writer) -> bool:
        def fetch():
            snap = self.server.metrics()
            out = {
                "status": "draining" if self._draining else "ok",
                # the serving-vs-draining contract, explicit: load
                # balancers route on this field, and a draining door
                # also answers 503 + Retry-After below
                "state": "draining" if self._draining else "serving",
                "occupancy": snap["occupancy"],
                "queue_depth": snap["queue_depth"],
                "lanes_busy": snap["lanes_busy"],
                "lanes_total": snap["lanes_total"],
                "quarantined_devices": snap["quarantined_devices"],
                "frontdoor": {
                    "draining": self._draining,
                    "queued": self.sched.queued(),
                    "active_streams": self._active_streams,
                    "tenants": self.sched.snapshot(),
                },
            }
            # cluster mode: per-host identity + health (docs/serving.md,
            # "Cluster serving") — the duck-typed router surface
            info = getattr(self.server, "cluster_info", None)
            if callable(info):
                out["cluster"] = info()
            return out

        payload = await self._locked(fetch)
        if self._draining:
            # every drain-path 503 carries Retry-After (the same
            # occupancy-derived hint submits quote), so health-checking
            # clients and balancers back off instead of hammering
            hint = max(self.server.retry_after_hint(), 1.0)
            await self._respond(
                writer, 503, payload,
                extra_headers={"Retry-After": f"{hint:.3f}"},
            )
        else:
            await self._respond(writer, 200, payload)
        return True

    async def _get_status(self, req: _HttpRequest, writer) -> bool:
        def fetch():
            snap = self.server.metrics()
            snap["frontdoor"] = {
                "draining": self._draining,
                "queued": self.sched.queued(),
                "active_streams": self._active_streams,
                "tenants": self.sched.snapshot(),
            }
            return snap

        await self._respond(writer, 200, await self._locked(fetch))
        return True

    async def _get_metrics(self, req: _HttpRequest, writer) -> bool:
        text = await self._locked(self.server.prometheus_metrics)
        await self._respond(
            writer, 200, text,
            content_type="text/plain; version=0.0.4",
        )
        return True
