"""Tenant identification for the front door.

Deliberately minimal: the front door's security model is shared-secret
API keys mapping a connection to a TENANT (the unit every policy —
fairness weight, rate limit, quota, counters — attaches to), not user
identity. Deployments needing real authn put a terminating proxy in
front and pass the tenant through; this layer only has to be
unambiguous and impossible to spoof ACROSS tenants that hold keys.

Resolution order (first match wins):

1. ``Authorization: Bearer <key>`` or ``X-API-Key: <key>`` — looked up
   against the tenants' ``api_key`` values; an unknown key is a 401.
2. ``X-Tenant: <name>`` — accepted only for tenants configured WITHOUT
   an ``api_key`` (open tenants); naming a keyed tenant without its
   key is a 403, an unknown name a 401.
3. No credentials: the single open tenant if exactly one exists (the
   zero-config case — no tenants file means one implicit ``default``
   tenant), else a 401 naming what is required.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Tuple

from lens_tpu.frontdoor.tenants import TenantConfig


class AuthError(Exception):
    """Refused tenant resolution; ``status`` is the HTTP code (401
    unknown/missing credentials, 403 wrong credentials for a named
    tenant)."""

    def __init__(self, status: int, message: str):
        self.status = int(status)
        super().__init__(message)


class Authenticator:
    """Header → :class:`TenantConfig` resolution over one tenant table."""

    def __init__(self, tenants: Mapping[str, TenantConfig]):
        self.tenants = dict(tenants)
        self._by_key: Dict[str, TenantConfig] = {
            cfg.api_key: cfg
            for cfg in self.tenants.values()
            if cfg.api_key is not None
        }
        self._open = [
            cfg for cfg in self.tenants.values() if cfg.api_key is None
        ]

    @staticmethod
    def _credentials(
        headers: Mapping[str, str]
    ) -> Tuple[Optional[str], Optional[str]]:
        """(api_key, claimed_tenant_name) from the request headers
        (header names lower-cased by the HTTP layer)."""
        key: Optional[str] = None
        auth = headers.get("authorization")
        if auth is not None:
            scheme, _, value = auth.partition(" ")
            if scheme.lower() != "bearer" or not value.strip():
                raise AuthError(
                    401,
                    "malformed Authorization header (expected "
                    "'Bearer <api-key>')",
                )
            key = value.strip()
        if key is None:
            key = headers.get("x-api-key")
        return key, headers.get("x-tenant")

    def resolve(self, headers: Mapping[str, str]) -> TenantConfig:
        key, claimed = self._credentials(headers)
        if key is not None:
            cfg = self._by_key.get(key)
            if cfg is None:
                raise AuthError(401, "unknown api key")
            if claimed is not None and claimed != cfg.name:
                raise AuthError(
                    403,
                    f"api key belongs to tenant {cfg.name!r}, not "
                    f"{claimed!r}",
                )
            return cfg
        if claimed is not None:
            cfg = self.tenants.get(claimed)
            if cfg is None:
                raise AuthError(401, f"unknown tenant {claimed!r}")
            if cfg.api_key is not None:
                raise AuthError(
                    403,
                    f"tenant {claimed!r} requires its api key "
                    f"(Authorization: Bearer ...)",
                )
            return cfg
        if len(self._open) == 1:
            return self._open[0]
        if self._open:
            raise AuthError(
                401,
                f"no credentials and {len(self._open)} open tenants "
                f"configured — name one with X-Tenant",
            )
        raise AuthError(
            401,
            "no credentials (every configured tenant requires an api "
            "key; send Authorization: Bearer <key>)",
        )
