"""Server-sent-event record streaming for the front door.

``GET /v1/requests/{rid}/stream`` answers with an SSE body whose
``record`` events carry the request's emit-log frames — the RAW bytes,
base64-armored, exactly as they sit in the request's ``.lens`` file.
The stream rides :func:`lens_tpu.emit.log.tail_frames`'s
reader-while-writer contract (only complete frames are ever sent; a
torn tail is re-read once the writer finishes it), so the
concatenation of every ``record`` event's decoded bytes is
BYTE-IDENTICAL to the finished log file — the serving determinism
contract surviving the hop over HTTP, pinned in
tests/test_frontdoor.py down to the stochastic composites.

Event vocabulary (in order):

- ``meta``: one JSON object ``{rid, status}`` when the stream opens;
- ``record``: one base64 line per complete log frame (header record
  first, then one SEGMENT record per streamed window);
- ``reset``: the request's result stream RESTARTED from scratch — a
  device quarantine displaced it onto a surviving shard and its sink
  regenerates the complete stream (docs/serving.md, "Mesh serving &
  device failover"). The client discards everything received so far;
  the re-streamed bytes are, by the failover contract, what a
  never-faulted run would have produced;
- ``end``: one JSON object ``{status, error}`` once the request is
  terminal AND its records are durably down (the server's
  per-request stream-completion mark — status alone runs ahead of
  the sink under the pipeline); the connection closes after it.

Comment lines (``: keepalive``) are emitted through long quiet gaps so
proxies do not reap an idle-but-healthy stream.
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
from typing import Any, AsyncIterator, Callable, Dict, Optional

from lens_tpu.emit.log import tail_frames


def sse_event(event: str, data: str) -> bytes:
    """One SSE frame (single-line data — base64/JSON never embeds a
    newline here)."""
    return f"event: {event}\ndata: {data}\n\n".encode()


def sse_comment(text: str = "keepalive") -> bytes:
    return f": {text}\n\n".encode()


async def record_events(
    state: Callable[[], Dict[str, Any]],
    poll_s: float = 0.02,
    heartbeat_s: float = 15.0,
    on_bytes: Optional[Callable[[int], None]] = None,
) -> AsyncIterator[bytes]:
    """Yield the SSE byte chunks of one request's record stream.

    ``state()`` is the front door's lock-free ticket probe: a dict
    with ``status`` (lifecycle string or ``"queued"`` while still at
    the front door), ``terminal`` (bool), ``streamed`` (records
    durably down — gates the ``end`` event), ``path`` (the result log,
    None before admission / for sinkless failures) and ``error``.
    ``on_bytes`` observes each record event's RAW frame size (the
    per-tenant ``streamed_bytes`` counter).
    """
    st = state()
    yield sse_event(
        "meta", json.dumps({"rid": st.get("rid"), "status": st["status"]})
    )
    offset = 0
    quiet = 0.0
    epoch = st.get("epoch", 0)
    while True:
        st = state()
        path = st.get("path")
        # decide BEFORE reading: if the completion mark is already
        # set, everything durable is visible to the read below, so
        # ending after it can never drop a tail frame
        done = bool(st["terminal"]) and (
            st.get("streamed", False) or path is None
        )
        sent = False
        exists = bool(path) and os.path.exists(path)
        if st.get("epoch", 0) != epoch or (
            exists and os.path.getsize(path) < offset
        ):
            # the request was displaced off a quarantined device and
            # its sink restarted from scratch (or the file shrank
            # under us, same thing): re-read from zero and tell the
            # client to discard what it has
            epoch = st.get("epoch", 0)
            offset = 0
            yield sse_event(
                "reset", json.dumps({"reason": "stream restarted"})
            )
        if exists:
            frames, offset = tail_frames(path, offset)
            for raw in frames:
                if on_bytes is not None:
                    on_bytes(len(raw))
                yield sse_event(
                    "record", base64.b64encode(raw).decode()
                )
                sent = True
        if done:
            yield sse_event(
                "end",
                json.dumps(
                    {"status": st["status"], "error": st.get("error")}
                ),
            )
            return
        if sent:
            quiet = 0.0
        else:
            quiet += poll_s
            if quiet >= heartbeat_s:
                quiet = 0.0
                yield sse_comment()
        await asyncio.sleep(poll_s)


def decode_record_events(body: bytes):
    """Client-side helper (tests, bench): parse an SSE body into
    ``(raw_frame_bytes, end_object)`` — the inverse of
    :func:`record_events`. Raises if the stream carries no ``end``
    event (a torn stream must not read as a complete one)."""
    raw = b""
    end_obj = None
    event = None
    for line in body.decode().split("\n"):
        if line.startswith("event: "):
            event = line[len("event: "):]
        elif line.startswith("data: "):
            data = line[len("data: "):]
            if event == "record":
                raw += base64.b64decode(data)
            elif event == "reset":
                raw = b""  # stream restarted after device failover
            elif event == "end":
                end_obj = json.loads(data)
    if end_obj is None:
        raise ValueError("SSE stream carried no 'end' event (torn?)")
    return raw, end_obj
