"""lens_tpu.frontdoor: the multi-tenant async HTTP front door.

A thin asyncio HTTP/1.1 layer (stdlib only) over a resident
:class:`~lens_tpu.serve.server.SimServer`: submit / status / SSE
record streaming / cancel plus ``/metrics``, ``/healthz`` and
``/v1/status``, with per-tenant weighted fair-share admission,
priority lanes, token-bucket rate limits, in-flight quotas, and honest
HTTP backpressure (429 + Retry-After from the server's
occupancy-derived hint). See docs/serving.md, "Front door".

Entry points: ``python -m lens_tpu frontdoor --port 8080 --tenants
tenants.json`` or in-process::

    server = SimServer.single_bucket(
        "toggle_colony", lanes=8, sink="log", out_dir="out/fd")
    with FrontDoor(server, tenants="tenants.json") as fd:
        ...  # http://127.0.0.1:{fd.port}/v1/requests
"""

from lens_tpu.frontdoor.app import FRONTDOOR_TRACK, FrontDoor
from lens_tpu.frontdoor.auth import AuthError, Authenticator
from lens_tpu.frontdoor.streams import (
    decode_record_events,
    record_events,
    sse_event,
)
from lens_tpu.frontdoor.tenants import (
    Entry,
    TenantConfig,
    TenantQueueFull,
    TenantScheduler,
    TokenBucket,
    load_tenants,
)

__all__ = [
    "FRONTDOOR_TRACK",
    "AuthError",
    "Authenticator",
    "Entry",
    "FrontDoor",
    "TenantConfig",
    "TenantQueueFull",
    "TenantScheduler",
    "TokenBucket",
    "decode_record_events",
    "load_tenants",
    "record_events",
    "sse_event",
]
