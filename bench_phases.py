"""Per-phase wall-clock breakdown of the flagship windows (VERDICT r4
missing #6: "bench_mfu names no bottleneck").

The exchange-window step is gather -> biology -> scatter -> diffuse
(SURVEY.md §3.2's two hot loops plus the coupling). This bench times
three jitted programs per flagship config over the same simulated
window, each fenced with ``block_until_ready``:

- ``full``      — the real ``SpatialColony.run`` window;
- ``biology``   — the same colony stepped WITHOUT the lattice
  (``Colony.run``: vmapped processes + division bookkeeping only);
- ``diffusion`` — the lattice field program alone
  (``lax.scan`` of ``Lattice.step_fields`` over the window's steps,
  all substeps included).

``coupling = full - biology - diffusion`` then bounds the
gather/scatter/exchange overhead (it also absorbs measurement noise and
fusion differences — XLA may fuse phases inside ``full`` that the
isolated programs cannot, so small negative values mean "coupling is
free, the phases fuse"). The TPU run of this file is the trace-level
answer to "where does the window's time go"; the CPU record is the
methodology anchor.

A fourth program family isolates the EXPRESSION phase of config 4 (the
north-star scenario): the scavenger species' biology window with the
stochastic-expression process under each Poisson sampler
(``ops.sampling``) and with it dropped — the subtraction prices the
phase and the exact/hybrid ratio records the sampler fast-path win.

Writes BENCH_PHASES.json; one JSON line per config.
"""

import json
import time

import numpy as np

from lens_tpu.utils.platform import guard_accelerator_or_exit

WINDOW_S = 32.0


def _timed(fn, *args, reps=3):
    import jax

    out = jax.block_until_ready(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = jax.block_until_ready(fn(*args))
    return (time.perf_counter() - t0) / reps


def _config_rows(name, spatial, n, window_s):
    import jax
    import jax.numpy as jnp
    from jax import lax

    ss = spatial.initial_state(n, jax.random.PRNGKey(0))
    steps = int(round(window_s))

    full = jax.jit(
        lambda s: spatial.run(s, window_s, 1.0, emit_every=steps)[0]
    )
    biology = jax.jit(
        lambda c: spatial.colony.run(c, window_s, 1.0, emit_every=steps)[0]
    )
    diffusion = jax.jit(
        lambda f: lax.scan(
            lambda carry, _: (spatial.lattice.step_fields(carry), None),
            f,
            None,
            length=steps,
        )[0]
    )

    t_full = _timed(full, ss)
    t_bio = _timed(biology, ss.colony)
    t_dif = _timed(diffusion, ss.fields)
    coupling = t_full - t_bio - t_dif
    row = {
        "config": name,
        "agents": n,
        "window_s": window_s,
        "full_s": round(t_full, 4),
        "biology_s": round(t_bio, 4),
        "diffusion_s": round(t_dif, 4),
        "coupling_s": round(coupling, 4),
        "biology_share": round(t_bio / t_full, 3),
        "diffusion_share": round(t_dif / t_full, 3),
        "bottleneck": max(
            ("biology", t_bio), ("diffusion", t_dif), ("coupling", coupling),
            key=lambda kv: kv[1],
        )[0],
    }
    print(json.dumps(row), flush=True)
    return row


def _config4_expression_ab(window_s):
    """Expression-phase A/B for config 4 (the north-star scenario).

    The scavenger species carries the colony's only stochastic
    expression process, so its BIOLOGY-only window isolates the phase:
    time it with expression under each sampler (ops.sampling) and with
    the expression process dropped; ``expression_<sampler> = with -
    without`` is the phase cost, and the exact/hybrid ratio is the
    fast-path win the round-6 tentpole claims.
    """
    import jax

    from lens_tpu.models.composites import mixed_species_lattice

    n = 51200  # the config-4 scavenger capacity (BASELINE.json)
    times = {}
    for label, overrides in (
        ("none", {"scavenger": {"expression": None}}),
        ("exact", {"sampler": "exact"}),
        ("hybrid", {}),  # composite default
    ):
        multi, _ = mixed_species_lattice(
            {
                "capacity": {"ecoli": 64, "scavenger": n},
                "shape": (256, 256),
                **overrides,
            }
        )
        colony = multi.species["scavenger"].colony
        cs = colony.initial_state(n, key=jax.random.PRNGKey(0))
        steps = int(round(window_s))
        biology = jax.jit(
            lambda s, c=colony: c.run(s, window_s, 1.0, emit_every=steps)[0]
        )
        times[label] = _timed(biology, cs)
    expr_exact = times["exact"] - times["none"]
    expr_hybrid = times["hybrid"] - times["none"]
    row = {
        "config": "4-expression",
        "agents": n,
        "window_s": window_s,
        "biology_none_s": round(times["none"], 4),
        "biology_exact_s": round(times["exact"], 4),
        "biology_hybrid_s": round(times["hybrid"], 4),
        "expression_exact_s": round(expr_exact, 4),
        "expression_hybrid_s": round(expr_hybrid, 4),
        "expression_speedup": round(expr_exact / max(expr_hybrid, 1e-9), 2),
    }
    print(json.dumps(row), flush=True)
    return row


def main():
    guard_accelerator_or_exit()
    import jax

    from lens_tpu.models.composites import ecoli_lattice, rfba_lattice

    backend = jax.default_backend()
    window_s = WINDOW_S if backend != "cpu" else 8.0
    rows = []

    rows.append(_config4_expression_ab(window_s))

    spatial2, _ = ecoli_lattice({"capacity": 10240})
    rows.append(_config_rows("2", spatial2, 10240, window_s))

    spatial3, _ = rfba_lattice(
        {
            "capacity": 1024,
            "shape": (64, 64),
            "metabolism": {"network": "ecoli_core"},
            "expression": {"genes": "ecoli_core"},
        }
    )
    rows.append(_config_rows("3b", spatial3, 1024, window_s))

    with open("BENCH_PHASES.json", "w") as f:
        json.dump(
            {
                "backend": backend,
                "device_kind": jax.devices()[0].device_kind,
                "note": (
                    "fenced jitted programs over the same window; "
                    "coupling = full - biology - diffusion bounds the "
                    "gather/scatter/exchange cost and absorbs fusion "
                    "differences (small negative = phases fuse for free)"
                ),
                "rows": rows,
            },
            f,
            indent=1,
        )


if __name__ == "__main__":
    main()
