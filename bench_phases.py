"""Per-phase wall-clock breakdown of the flagship windows (VERDICT r4
missing #6: "bench_mfu names no bottleneck").

The exchange-window step is gather -> biology -> scatter -> diffuse
(SURVEY.md §3.2's two hot loops plus the coupling). This bench times
three jitted programs per flagship config over the same simulated
window, each fenced with ``block_until_ready``:

- ``full``      — the real ``SpatialColony.run`` window;
- ``biology``   — the same colony stepped WITHOUT the lattice
  (``Colony.run``: vmapped processes + division bookkeeping only);
- ``diffusion`` — the lattice field program alone
  (``lax.scan`` of ``Lattice.step_fields`` over the window's steps,
  all substeps included).

``coupling = full - biology - diffusion`` then bounds the
gather/scatter/exchange overhead (it also absorbs measurement noise and
fusion differences — XLA may fuse phases inside ``full`` that the
isolated programs cannot, so small negative values mean "coupling is
free, the phases fuse"). The TPU run of this file is the trace-level
answer to "where does the window's time go"; the CPU record is the
methodology anchor.

Writes BENCH_PHASES.json; one JSON line per config.
"""

import json
import time

import numpy as np

from lens_tpu.utils.platform import guard_accelerator_or_exit

WINDOW_S = 32.0


def _timed(fn, *args, reps=3):
    import jax

    out = jax.block_until_ready(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = jax.block_until_ready(fn(*args))
    return (time.perf_counter() - t0) / reps


def _config_rows(name, spatial, n, window_s):
    import jax
    import jax.numpy as jnp
    from jax import lax

    ss = spatial.initial_state(n, jax.random.PRNGKey(0))
    steps = int(round(window_s))

    full = jax.jit(
        lambda s: spatial.run(s, window_s, 1.0, emit_every=steps)[0]
    )
    biology = jax.jit(
        lambda c: spatial.colony.run(c, window_s, 1.0, emit_every=steps)[0]
    )
    diffusion = jax.jit(
        lambda f: lax.scan(
            lambda carry, _: (spatial.lattice.step_fields(carry), None),
            f,
            None,
            length=steps,
        )[0]
    )

    t_full = _timed(full, ss)
    t_bio = _timed(biology, ss.colony)
    t_dif = _timed(diffusion, ss.fields)
    coupling = t_full - t_bio - t_dif
    row = {
        "config": name,
        "agents": n,
        "window_s": window_s,
        "full_s": round(t_full, 4),
        "biology_s": round(t_bio, 4),
        "diffusion_s": round(t_dif, 4),
        "coupling_s": round(coupling, 4),
        "biology_share": round(t_bio / t_full, 3),
        "diffusion_share": round(t_dif / t_full, 3),
        "bottleneck": max(
            ("biology", t_bio), ("diffusion", t_dif), ("coupling", coupling),
            key=lambda kv: kv[1],
        )[0],
    }
    print(json.dumps(row), flush=True)
    return row


def main():
    guard_accelerator_or_exit()
    import jax

    from lens_tpu.models.composites import ecoli_lattice, rfba_lattice

    backend = jax.default_backend()
    window_s = WINDOW_S if backend != "cpu" else 8.0
    rows = []

    spatial2, _ = ecoli_lattice({"capacity": 10240})
    rows.append(_config_rows("2", spatial2, 10240, window_s))

    spatial3, _ = rfba_lattice(
        {
            "capacity": 1024,
            "shape": (64, 64),
            "metabolism": {"network": "ecoli_core"},
            "expression": {"genes": "ecoli_core"},
        }
    )
    rows.append(_config_rows("3b", spatial3, 1024, window_s))

    with open("BENCH_PHASES.json", "w") as f:
        json.dump(
            {
                "backend": backend,
                "device_kind": jax.devices()[0].device_kind,
                "note": (
                    "fenced jitted programs over the same window; "
                    "coupling = full - biology - diffusion bounds the "
                    "gather/scatter/exchange cost and absorbs fusion "
                    "differences (small negative = phases fuse for free)"
                ),
                "rows": rows,
            },
            f,
            indent=1,
        )


if __name__ == "__main__":
    main()
