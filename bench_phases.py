"""Per-phase wall-clock breakdown of the flagship windows (VERDICT r4
missing #6: "bench_mfu names no bottleneck").

The exchange-window step is gather -> biology -> scatter -> diffuse
(SURVEY.md §3.2's two hot loops plus the coupling). This bench times
jitted programs per flagship config over the same simulated window,
each fenced with ``block_until_ready``:

- ``full``      — the real ``SpatialColony.run`` window, under BOTH
  coupling implementations (round 7): ``coupling="fused"`` (the
  CouplingPlan one-pass gather/scatter, the default) and
  ``coupling="reference"`` (the original per-molecule three-message
  step, the oracle);
- ``biology``   — the same colony stepped WITHOUT the lattice
  (``Colony.run``: vmapped processes + division bookkeeping only);
- ``diffusion`` — the lattice field program alone
  (``lax.scan`` of ``Lattice.step_fields`` over the window's steps,
  all substeps included).

``coupling = full - biology - diffusion`` then bounds the
gather/scatter/exchange overhead (it also absorbs measurement noise and
fusion differences — XLA may fuse phases inside ``full`` that the
isolated programs cannot, so small negative values mean "coupling is
free, the phases fuse"). ``coupling_speedup`` is the reference/fused
ratio of that bound — the round-7 tentpole's committed number. The TPU
run of this file is the trace-level answer to "where does the window's
time go"; the CPU record is the methodology anchor.

A fourth program family isolates the EXPRESSION phase of config 4 (the
north-star scenario): the scavenger species' biology window with the
stochastic-expression process under each Poisson sampler
(``ops.sampling``) and with it dropped — the subtraction prices the
phase and the exact/hybrid ratio records the sampler fast-path win.

A fifth isolates config 4's COUPLING phase (round 7): the full
mixed-species window under each coupling implementation, with the
round-6 hybrid sampler active (the post-sampler regime where coupling
is the residual bottleneck), minus per-species biology and diffusion.

Timing: each program is warmed (compile + run), then timed ``reps``
times and the MINIMUM is reported — this box's wall-clock wanders
+/-20% with cgroup cpu-shares scheduling, and the minimum is the
stable estimator of the program's actual cost (means drift with
whatever else the host ran that second).

Writes BENCH_PHASES.json; one JSON line per config.
"""

import json
import time

import numpy as np

from lens_tpu.utils.platform import guard_accelerator_or_exit

WINDOW_S = 32.0


def _timed(fn, *args, reps=5):
    import jax

    out = jax.block_until_ready(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _timed_multi(progs, reps=5):
    """Min-of-reps for SEVERAL programs with INTERLEAVED reps.

    ``progs``: list of (fn, arg). A phase row is built from DIFFERENCES
    of these programs' times (coupling = full - biology - diffusion;
    speedup = reference vs fused), and this box's wall-clock drifts
    +/-20% over seconds — timing each program in its own block lets the
    drift land entirely on one term. Round-robin reps spread it evenly;
    the per-program minimum then estimates each program's true cost
    under the SAME conditions.
    """
    import jax

    for fn, arg in progs:
        jax.block_until_ready(fn(arg))  # compile + warm
    best = [float("inf")] * len(progs)
    for _ in range(reps):
        for i, (fn, arg) in enumerate(progs):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(arg))
            best[i] = min(best[i], time.perf_counter() - t0)
    return best


#: ratio floor: a subtraction-derived phase bound below ~1 ms is inside
#: this box's fence/dispatch noise; ratios against it are meaningless.
_RATIO_FLOOR_S = 1e-3


def _config_rows(name, build_spatial, n, window_s):
    """Phase rows for a single-species lattice config.

    ``build_spatial(coupling)`` -> a fresh SpatialColony wired with that
    coupling implementation (same biology, same lattice parameters).
    """
    import jax
    from jax import lax

    spatial = {c: build_spatial(c) for c in ("fused", "reference")}
    ss = spatial["fused"].initial_state(n, jax.random.PRNGKey(0))
    steps = int(round(window_s))

    full = {
        c: jax.jit(
            lambda s, sp=sp: sp.run(s, window_s, 1.0, emit_every=steps)[0]
        )
        for c, sp in spatial.items()
    }
    sp = spatial["fused"]
    biology = jax.jit(
        lambda c: sp.colony.run(c, window_s, 1.0, emit_every=steps)[0]
    )
    diffusion = jax.jit(
        lambda f: lax.scan(
            lambda carry, _: (sp.lattice.step_fields(carry), None),
            f,
            None,
            length=steps,
        )[0]
    )
    t_full = {}
    t_full["fused"], t_full["reference"], t_bio, t_dif = _timed_multi(
        [
            (full["fused"], ss),
            (full["reference"], ss),
            (biology, ss.colony),
            (diffusion, ss.fields),
        ]
    )
    coupling_f = t_full["fused"] - t_bio - t_dif
    coupling_r = t_full["reference"] - t_bio - t_dif
    row = {
        "config": name,
        "agents": n,
        "window_s": window_s,
        "full_s": round(t_full["fused"], 4),
        "full_reference_s": round(t_full["reference"], 4),
        "biology_s": round(t_bio, 4),
        "diffusion_s": round(t_dif, 4),
        "coupling_s": round(coupling_f, 4),
        "coupling_reference_s": round(coupling_r, 4),
        "coupling_delta_s": round(
            t_full["reference"] - t_full["fused"], 4
        ),
        "coupling_speedup": round(
            coupling_r / max(coupling_f, _RATIO_FLOOR_S), 2
        ),
        "biology_share": round(t_bio / t_full["fused"], 3),
        "diffusion_share": round(t_dif / t_full["fused"], 3),
        "bottleneck": max(
            ("biology", t_bio), ("diffusion", t_dif),
            ("coupling", coupling_f),
            key=lambda kv: kv[1],
        )[0],
    }
    print(json.dumps(row), flush=True)
    return row


def _config4_expression_ab(window_s):
    """Expression-phase A/B for config 4 (the north-star scenario).

    The scavenger species carries the colony's only stochastic
    expression process, so its BIOLOGY-only window isolates the phase:
    time it with expression under each sampler (ops.sampling) and with
    the expression process dropped; ``expression_<sampler> = with -
    without`` is the phase cost, and the exact/hybrid ratio is the
    fast-path win the round-6 tentpole claims.
    """
    import jax

    from lens_tpu.models.composites import mixed_species_lattice

    n = 51200  # the config-4 scavenger capacity (BASELINE.json)
    times = {}
    for label, overrides in (
        ("none", {"scavenger": {"expression": None}}),
        ("exact", {"sampler": "exact"}),
        ("hybrid", {}),  # composite default
    ):
        multi, _ = mixed_species_lattice(
            {
                "capacity": {"ecoli": 64, "scavenger": n},
                "shape": (256, 256),
                **overrides,
            }
        )
        colony = multi.species["scavenger"].colony
        cs = colony.initial_state(n, key=jax.random.PRNGKey(0))
        steps = int(round(window_s))
        biology = jax.jit(
            lambda s, c=colony: c.run(s, window_s, 1.0, emit_every=steps)[0]
        )
        times[label] = _timed(biology, cs, reps=3)
    expr_exact = times["exact"] - times["none"]
    expr_hybrid = times["hybrid"] - times["none"]
    row = {
        "config": "4-expression",
        "agents": n,
        "window_s": window_s,
        "biology_none_s": round(times["none"], 4),
        "biology_exact_s": round(times["exact"], 4),
        "biology_hybrid_s": round(times["hybrid"], 4),
        "expression_exact_s": round(expr_exact, 4),
        "expression_hybrid_s": round(expr_hybrid, 4),
        "expression_speedup": round(expr_exact / max(expr_hybrid, 1e-9), 2),
    }
    print(json.dumps(row), flush=True)
    return row


def _config4_coupling(window_s):
    """Coupling-phase A/B for config 4 with the round-6 hybrid sampler
    ACTIVE — the post-sampler regime the round-7 tentpole targets: the
    expression hot loop fell ~10x in round 6, so the residual window is
    coupling-heavy. ``coupling = full - sum(per-species biology) -
    diffusion`` per coupling implementation.
    """
    import jax
    from jax import lax

    from lens_tpu.models.composites import mixed_species_lattice

    n_each = 51200
    steps = int(round(window_s))
    built = {}
    for coupling in ("fused", "reference"):
        built[coupling], _ = mixed_species_lattice(
            {
                "capacity": {"ecoli": n_each, "scavenger": n_each},
                "shape": (256, 256),
                "coupling": coupling,
            }
        )
    multi_f = built["fused"]
    ms = multi_f.initial_state(
        {"ecoli": n_each, "scavenger": n_each}, jax.random.PRNGKey(0)
    )
    full = {
        c: jax.jit(
            lambda s, m=m: m.run(s, window_s, 1.0, emit_every=steps)[0]
        )
        for c, m in built.items()
    }
    progs = [(full["fused"], ms), (full["reference"], ms)]
    for name, sp in multi_f.species.items():
        colony = sp.colony
        biology = jax.jit(
            lambda c, co=colony: co.run(c, window_s, 1.0, emit_every=steps)[0]
        )
        progs.append((biology, ms.species[name]))
    diffusion = jax.jit(
        lambda f: lax.scan(
            lambda carry, _: (multi_f.lattice.step_fields(carry), None),
            f,
            None,
            length=steps,
        )[0]
    )
    progs.append((diffusion, ms.fields))
    times = _timed_multi(progs, reps=4)
    t_full = {"fused": times[0], "reference": times[1]}
    t_bio = sum(times[2:-1])
    t_dif = times[-1]
    coupling_f = t_full["fused"] - t_bio - t_dif
    coupling_r = t_full["reference"] - t_bio - t_dif
    row = {
        "config": "4-coupling",
        "agents": 2 * n_each,
        "window_s": window_s,
        "full_s": round(t_full["fused"], 4),
        "full_reference_s": round(t_full["reference"], 4),
        "biology_s": round(t_bio, 4),
        "diffusion_s": round(t_dif, 4),
        "coupling_s": round(coupling_f, 4),
        "coupling_reference_s": round(coupling_r, 4),
        "coupling_delta_s": round(
            t_full["reference"] - t_full["fused"], 4
        ),
        "coupling_speedup": round(
            coupling_r / max(coupling_f, _RATIO_FLOOR_S), 2
        ),
    }
    print(json.dumps(row), flush=True)
    return row


def main():
    guard_accelerator_or_exit()
    import jax

    from lens_tpu.models.composites import ecoli_lattice, rfba_lattice

    backend = jax.default_backend()
    window_s = WINDOW_S if backend != "cpu" else 8.0
    rows = []

    rows.append(_config4_expression_ab(window_s))
    rows.append(_config4_coupling(window_s))

    rows.append(
        _config_rows(
            "2",
            lambda coupling: ecoli_lattice(
                {"capacity": 10240, "coupling": coupling}
            )[0],
            10240,
            window_s,
        )
    )

    rows.append(
        _config_rows(
            "3b",
            lambda coupling: rfba_lattice(
                {
                    "capacity": 1024,
                    "shape": (64, 64),
                    "metabolism": {"network": "ecoli_core"},
                    "expression": {"genes": "ecoli_core"},
                    "coupling": coupling,
                }
            )[0],
            1024,
            window_s,
        )
    )

    with open("BENCH_PHASES.json", "w") as f:
        json.dump(
            {
                "backend": backend,
                "device_kind": jax.devices()[0].device_kind,
                "note": (
                    "fenced jitted programs over the same window, min of "
                    "timed reps after a warm run; the fused/reference "
                    "full windows interleave their reps so wall-clock "
                    "drift cannot land on one side. coupling = full - "
                    "biology - diffusion bounds the gather/scatter/"
                    "exchange cost and absorbs fusion differences (small "
                    "negative = phases fuse for free); coupling_speedup "
                    "= reference/fused on that bound (round-7 "
                    "CouplingPlan tentpole); coupling_delta_s = "
                    "full_reference - full_fused is the drift-robust "
                    "absolute win (the shared biology cancels exactly), "
                    "the honest number for biology-dominated configs "
                    "(3b) where the subtraction bound is noise-limited"
                ),
                "rows": rows,
            },
            f,
            indent=1,
        )


if __name__ == "__main__":
    main()
