"""On-device (real TPU) test session setup.

These tests are OPT-IN: the default suite (`tests/`, pyproject
``testpaths``) pins the CPU platform because this box's TPU relay can
hang backend init (see lens_tpu.utils.platform). Run these explicitly
when the chip is reachable::

    LENS_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/ -q

Collection itself never initializes a backend, so a down relay cannot
wedge pytest — the guard skips before any jax device use.
"""

from __future__ import annotations

import os

import pytest


def pytest_collection_modifyitems(config, items):
    if os.environ.get("LENS_TPU_DEVICE_TESTS"):
        return
    skip = pytest.mark.skip(
        reason="on-device TPU tests are opt-in: set LENS_TPU_DEVICE_TESTS=1"
    )
    for item in items:
        item.add_marker(skip)


@pytest.fixture(scope="session")
def tpu_device():
    """The real TPU device, or skip if no TPU backend comes up.

    Two relay failure modes, both skips rather than errors: backend
    init FALLS BACK to CPU (platform check below), or — since
    2026-07-31 — it raises fast (``Backend 'axon' is not in the list
    of known backends``: the PJRT plugin fails registration when the
    relay is dead)."""
    import jax

    try:
        devices = jax.devices()
    except RuntimeError as e:
        pytest.skip(f"accelerator backend init failed: {e}")
    if devices[0].platform not in ("tpu", "axon"):
        pytest.skip(f"default backend is {devices[0].platform}, not TPU")
    return devices[0]
