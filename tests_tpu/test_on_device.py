"""On-device numerics: the compiled TPU kernels, not their CPU shadows.

The default suite validates every kernel in interpret/CPU mode; these
tests re-check the claims that only hold (or only break) on real TPU
hardware (VERDICT r2 weak #3):

- the compiled Pallas diffusion kernel matches the XLA stencil on-device;
- the float32-pinned interior-point LP converges on the ecoli_core
  network (the bf16 default silently breaks it — the regression this
  guards is the one measured in ops/linprog.py);
- one full config-2 window runs on-device and stays finite.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class TestPallasStencil:
    def test_pallas_matches_xla_on_device(self, tpu_device):
        from lens_tpu.ops.diffusion import diffuse_pallas, diffuse_xla

        key = jax.random.PRNGKey(0)
        for size in (64, 256):
            fields = jax.random.uniform(key, (2, size, size), jnp.float32)
            coeff = jnp.asarray([0.02, 0.07], jnp.float32)
            out_p = jax.jit(
                lambda f, c: diffuse_pallas(f, c, n_substeps=27)
            )(fields, coeff)
            out_x = jax.jit(
                lambda f, c: diffuse_xla(f, c, n_substeps=27)
            )(fields, coeff)
            np.testing.assert_allclose(
                np.asarray(out_p), np.asarray(out_x), rtol=2e-5, atol=2e-6
            )
            # mass conservation on-device (no-flux boundaries)
            np.testing.assert_allclose(
                float(jnp.sum(out_p)), float(jnp.sum(fields)), rtol=1e-5
            )


class TestTiledStencilOnDevice:
    def test_tiled_matches_xla_beyond_vmem(self, tpu_device):
        """1024^2 exceeds the whole-slab VMEM budget — the halo-overlap
        tiled kernel must agree with XLA on the compiled TPU path."""
        from lens_tpu.ops.diffusion import (
            _fits_vmem,
            diffuse_pallas_tiled,
            diffuse_xla,
        )

        fields = jax.random.uniform(
            jax.random.PRNGKey(1), (2, 1024, 1024), jnp.float32
        )
        assert not _fits_vmem(fields)
        alpha = jnp.asarray([0.05, 0.135], jnp.float32)
        out_t = jax.jit(
            lambda f: diffuse_pallas_tiled(f, alpha, n_substeps=27)
        )(fields)
        out_x = jax.jit(lambda f: diffuse_xla(f, alpha, 27))(fields)
        np.testing.assert_allclose(
            np.asarray(out_t), np.asarray(out_x), rtol=2e-5, atol=2e-5
        )


class TestADIOnDevice:
    def test_adi_window_on_device(self, tpu_device):
        """One ADI window on the chip: conserves mass, stays nonnegative,
        and tracks the dense-substep FTCS oracle."""
        from lens_tpu.ops.adi import adi_plan, diffuse_adi
        from lens_tpu.ops.diffusion import diffuse_xla

        alpha = np.asarray([6.0, 1.5])
        f = jax.random.uniform(
            jax.random.PRNGKey(2), (2, 256, 256), jnp.float32, 0.0, 10.0
        )
        f = diffuse_xla(f, jnp.full((2,), 0.2), 10)  # smooth
        plan = adi_plan(alpha, 256, 256)
        out = jax.jit(lambda g: diffuse_adi(g, plan))(f)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(out, axis=(1, 2))),
            np.asarray(jnp.sum(f, axis=(1, 2))),
            rtol=1e-5,
        )
        assert float(jnp.min(out)) >= 0.0
        ref = diffuse_xla(f, jnp.asarray(alpha / 600, jnp.float32), 600)
        err = float(
            jnp.max(jnp.abs(out - ref)) / (jnp.max(jnp.abs(ref)) + 1e-9)
        )
        assert err < 0.08, err


class TestLinprogOnDevice:
    def test_ecoli_core_batch_converges(self, tpu_device):
        from lens_tpu.processes.fba_metabolism import FBAMetabolism
        from lens_tpu.ops.linprog import flux_balance

        proc = FBAMetabolism(
            {"network": "ecoli_core", "lp_leak": 1.5e-3, "lp_tol": 1e-4}
        )
        rng = np.random.default_rng(0)
        ext = jnp.asarray(
            rng.uniform(0.0, 20.0, size=(256, len(proc.external))).astype(
                np.float32
            )
        )
        lbs, ubs = jax.vmap(lambda e: proc.regulated_bounds(e, 1.0))(ext)
        sol = jax.jit(
            jax.vmap(
                lambda l, u: flux_balance(
                    proc.stoichiometry, proc.objective, l, u,
                    n_iter=45, tol=1e-4, leak=1.5e-3,
                )
            )
        )(lbs, ubs)
        sol = jax.block_until_ready(sol)
        assert float(jnp.mean(sol.converged.astype(jnp.float32))) == 1.0
        # the adaptive exit must actually fire on-device too
        assert int(jnp.max(sol.iterations)) < 45
        assert bool(jnp.all(sol.objective >= -1e-6))


class TestPDLPOnDevice:
    def test_ecoli_core_batch_converges_pdlp(self, tpu_device):
        """The first-order solver's batched matvecs ([N,R]@[R,M] — the
        MXU shape) must converge on-chip at the FBA tolerance, agreeing
        with the IPM's objective on the same batch."""
        from lens_tpu.ops.linprog import flux_balance
        from lens_tpu.ops.pdlp import flux_balance_pdlp
        from lens_tpu.processes.fba_metabolism import FBAMetabolism

        proc = FBAMetabolism(
            {"network": "ecoli_core", "lp_leak": 1.5e-3, "lp_tol": 1e-4}
        )
        rng = np.random.default_rng(0)
        ext = jnp.asarray(
            rng.uniform(0.0, 20.0, size=(256, len(proc.external))).astype(
                np.float32
            )
        )
        lbs, ubs = jax.vmap(lambda e: proc.regulated_bounds(e, 1.0))(ext)
        pd = jax.jit(
            jax.vmap(
                lambda l, u: flux_balance_pdlp(
                    proc.stoichiometry, proc.objective, l, u,
                    n_iter=32768, tol=1e-4, leak=1.5e-3,
                )
            )
        )(lbs, ubs)
        pd = jax.block_until_ready(pd)
        assert float(jnp.mean(pd.converged.astype(jnp.float32))) == 1.0
        ipm = jax.jit(
            jax.vmap(
                lambda l, u: flux_balance(
                    proc.stoichiometry, proc.objective, l, u,
                    n_iter=45, tol=1e-4, leak=1.5e-3,
                )
            )
        )(lbs, ubs)
        ipm = jax.block_until_ready(ipm)
        np.testing.assert_allclose(
            np.asarray(pd.objective), np.asarray(ipm.objective),
            rtol=5e-3, atol=5e-4,
        )


class TestFlagshipWindow:
    def test_config2_window_finite(self, tpu_device):
        from lens_tpu.models import ecoli_lattice

        spatial, _ = ecoli_lattice({"capacity": 1024, "shape": (64, 64)})
        ss = spatial.initial_state(1024, jax.random.PRNGKey(0))
        window = jax.jit(
            lambda s: spatial.run(s, 8.0, 1.0, emit_every=8)[0]
        )
        out = jax.block_until_ready(window(ss))
        assert int(jnp.sum(out.colony.alive)) >= 1024
        for leaf in jax.tree.leaves(out.colony.agents):
            if jnp.issubdtype(leaf.dtype, jnp.floating):
                assert bool(jnp.isfinite(leaf).all())
        assert bool(jnp.isfinite(out.fields).all())


class TestFullNetworkOnDevice:
    """Round-4 float32 envelope on the REAL chip: the canonical 72x95
    e_coli_core must converge aerobic AND anaerobic, and the warm start
    must cut iterations without changing what converged means."""

    def _bounds(self, proc, env, n):
        rng = np.random.default_rng(3)
        base = np.zeros((n, len(proc.external)), np.float32)
        for e, mol in enumerate(proc.external):
            base[:, e] = env.get(mol, 0.0) * rng.uniform(0.8, 1.2, n)
        return jax.vmap(lambda e: proc.regulated_bounds(e, 1.0))(
            jnp.asarray(base)
        )

    def test_full_core_converges_both_regimes(self, tpu_device):
        from lens_tpu.ops.linprog import flux_balance
        from lens_tpu.processes.fba_metabolism import FBAMetabolism

        proc = FBAMetabolism(
            {"network": "ecoli_core_full", "lp_leak": 1.5e-3,
             "lp_tol": 1e-5, "lp_iterations": 45}
        )
        solve = jax.jit(
            jax.vmap(
                lambda l, u: flux_balance(
                    proc.stoichiometry, proc.objective, l, u,
                    n_iter=45, tol=1e-5, leak=1.5e-3,
                )
            )
        )
        for env, lo, hi in (
            ({"glc": 10.0, "o2": 50.0, "nh4": 50.0}, 0.07, 0.10),
            ({"glc": 10.0, "nh4": 50.0}, 0.015, 0.025),
        ):
            lbs, ubs = self._bounds(proc, env, 128)
            sol = jax.block_until_ready(solve(lbs, ubs))
            conv = float(jnp.mean(sol.converged.astype(jnp.float32)))
            assert conv == 1.0, (env, conv)
            mu = float(jnp.mean(sol.objective))
            assert lo < mu < hi, (env, mu)

    def test_warm_start_cuts_iterations_on_device(self, tpu_device):
        from lens_tpu.ops.linprog import flux_balance
        from lens_tpu.processes.fba_metabolism import FBAMetabolism

        proc = FBAMetabolism(
            {"network": "ecoli_core_full", "lp_leak": 1.5e-3,
             "lp_tol": 1e-5, "lp_iterations": 45}
        )
        lbs, ubs = self._bounds(
            proc, {"glc": 10.0, "o2": 50.0, "nh4": 50.0}, 128
        )
        cold = jax.jit(
            jax.vmap(
                lambda l, u: flux_balance(
                    proc.stoichiometry, proc.objective, l, u,
                    n_iter=45, tol=1e-5, leak=1.5e-3,
                )
            )
        )
        warm = jax.jit(
            jax.vmap(
                lambda l, u, w: flux_balance(
                    proc.stoichiometry, proc.objective, l, u,
                    n_iter=45, tol=1e-5, leak=1.5e-3, warm=w,
                )
            )
        )
        a = jax.block_until_ready(cold(lbs, ubs))
        b = jax.block_until_ready(warm(lbs, ubs, a.warm))
        assert float(jnp.mean(b.converged.astype(jnp.float32))) == 1.0
        # same problems re-solved from their own optimum: the warm pass
        # must be several times cheaper in max-lane iterations
        assert int(jnp.max(b.iterations)) <= int(jnp.max(a.iterations)) // 2
        # and land on the same objective to tolerance
        np.testing.assert_allclose(
            np.asarray(b.objective), np.asarray(a.objective), atol=2e-3
        )


class TestExpandedColonyWindowOnDevice:
    def test_config2_window_after_expansion_finite(self, tpu_device):
        """A capacity-expanded colony's next window runs clean on the
        chip (recompile at the new shape, frozen dead rows stay inert)."""
        from lens_tpu.models.composites import ecoli_lattice

        spatial, _ = ecoli_lattice(
            {"capacity": 512, "shape": (32, 32), "division": True,
             "growth": {"rate": 0.05}}
        )
        ss = spatial.initial_state(256, jax.random.PRNGKey(0))
        ss, _ = spatial.run(ss, 8.0, 1.0, emit_every=8)
        spatial2, ss2 = spatial.expanded(ss, 2)
        ss2, traj = spatial2.run(ss2, 8.0, 1.0, emit_every=8)
        assert int(ss2.colony.alive.shape[0]) == 1024
        assert bool(jnp.all(jnp.isfinite(ss2.fields)))
        assert bool(
            jnp.all(jnp.isfinite(traj["global"]["volume"]))
        )


class TestEnsembleOnDevice:
    def test_replicate_scan_runs_and_responds(self, tpu_device):
        """A parameter scan (replicate_overrides on the Ensemble axis)
        compiles and runs on the chip, and the scanned parameter produces
        a monotone on-device response — the feature's first hardware
        proof (built during a relay outage, CPU-validated only)."""
        from lens_tpu.colony import Colony, Ensemble
        from lens_tpu.models.composites import minimal_wcecoli

        colony = Colony(
            minimal_wcecoli({}), capacity=256,
            division_trigger=("global", "divide"),
        )
        doses = jnp.logspace(-1.5, 1.0, 8)
        ens = Ensemble(colony, 8)
        states = ens.initial_state(
            128,
            key=jax.random.PRNGKey(0),
            replicate_overrides={"metabolites": {"glc": doses}},
        )
        run = jax.jit(lambda s: ens.run(s, 60.0, 1.0, emit_every=60))
        final, traj = jax.block_until_ready(run(states))
        alive = np.asarray(final.alive)
        mass = (np.asarray(final.agents["global"]["mass"]) * alive).sum(
            axis=1
        )
        assert np.isfinite(mass).all()
        assert (np.diff(mass) >= 0).all() and mass[-1] > mass[0]


class TestCrossFeedingOnDevice:
    def test_xf_window_finite_and_feeds(self, tpu_device):
        """One cross-feeding window on the chip: the mixed rFBA+kinetic
        program compiles, stays finite, and the syntrophy chain moves
        (overflow acetate appears; built relay-down, CPU-validated)."""
        from lens_tpu.models.composites import rfba_cross_feeding

        multi, _ = rfba_cross_feeding(
            {"capacity": {"ecoli": 256, "scavenger": 256},
             "shape": (32, 32), "size": (32.0, 32.0)}
        )
        ms = multi.initial_state(
            {"ecoli": 128, "scavenger": 128}, jax.random.PRNGKey(0)
        )
        ace = multi.lattice.molecules.index("ace")
        ms, traj = jax.block_until_ready(
            jax.jit(lambda s: multi.run(s, 30.0, 1.0, emit_every=30))(ms)
        )
        assert bool(jnp.all(jnp.isfinite(ms.fields)))
        assert float(ms.fields[ace].sum()) > 0.0
        pool = ms.species["scavenger"].agents["cell"]["ace_internal"]
        assert float(pool.max()) > 0.0


class TestDeathOnDevice:
    def test_starving_window_dies_on_chip(self, tpu_device):
        """A starving flagship window on the chip: the death mask path
        compiles and the population monotonically collapses (built
        relay-down; CPU-validated in tests/test_parallel.py)."""
        from lens_tpu.models.composites import ecoli_lattice

        spatial, _ = ecoli_lattice(
            {"capacity": 256, "shape": (32, 32), "size": (32.0, 32.0),
             "division": False, "initial_glucose": 0.001,
             "death": {"threshold": 0.02}}
        )
        yolk = {"cell": {"glucose_internal": jnp.full(256, 0.05)}}
        ss = spatial.initial_state(256, jax.random.PRNGKey(0), overrides=yolk)
        ss, traj = jax.block_until_ready(
            jax.jit(lambda s: spatial.run(s, 30.0, 1.0, emit_every=10))(ss)
        )
        alive = np.asarray(traj["alive"]).sum(axis=1)
        assert alive[-1] < alive[0]
        assert (np.diff(alive) <= 0).all()
