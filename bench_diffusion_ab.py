"""A/B the diffusion stencil: Pallas kernel vs XLA scan, on real TPU.

SURVEY.md §7 step 5: "benchmark kernel vs pure-XLA baseline (keep
whichever wins at v1)". This script produces the recorded decision for
``ops.diffusion.diffuse(impl="auto")``:

- times the implementations at 64^2 / 256^2 / 1024^2 / 2048^2 (3
  molecules, a realistic exchange-window substep count per size): the
  whole-slab kernel while it fits VMEM, plus the halo-overlap tiled
  kernel (``diffuse_pallas_tiled``) at every size it supports — the
  beyond-VMEM contender;
- asserts every path agrees with XLA numerically ON DEVICE (same adds,
  same order — tests only checked interpret mode before);
- writes ``BENCH_DIFFUSION_AB.json`` with the winner per size.

Run on the TPU:  python bench_diffusion_ab.py
"""

from __future__ import annotations

import json
import os
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.ops.diffusion import (
    _fits_vmem,
    _tile_rows,
    diffuse_pallas,
    diffuse_pallas_tiled,
    diffuse_xla,
    stable_substeps,
)

SIZES = (64, 256, 1024, 2048)
M = 3
REPEATS = 5
#: windows chained INSIDE one jit call: the tunneled chip has ~3 ms of
#: per-dispatch latency, which would otherwise swamp the kernels (every
#: size measured a flat ~67 ms per call before amortization)
INNER_WINDOWS = 50


def chain(window):
    def run(f):
        out, _ = jax.lax.scan(lambda g, _: (window(g), None), f,
                              None, length=INNER_WINDOWS)
        return out

    return jax.jit(run)


def time_fn(fn, *args) -> float:
    """Seconds per WINDOW (dispatch amortized over INNER_WINDOWS)."""
    out = jax.block_until_ready(fn(*args))  # compile
    t0 = time.perf_counter()
    for _ in range(REPEATS):
        out = jax.block_until_ready(fn(*args))
    return (time.perf_counter() - t0) / (REPEATS * INNER_WINDOWS)


def main() -> None:
    from lens_tpu.utils.platform import guard_accelerator_or_exit

    guard_accelerator_or_exit()
    report = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "repeats": REPEATS,
        "results": [],
    }
    for n in SIZES:
        key = jax.random.PRNGKey(n)
        fields = jax.random.uniform(key, (M, n, n), minval=0.0, maxval=10.0)
        # a diffusion-limited window: D=600 um^2/s on 10 um bins, dt=1 s
        n_sub = stable_substeps(600.0, 1.0, 10.0)
        alpha = jnp.asarray([0.05, 0.1, 0.135])

        xla = chain(lambda f: diffuse_xla(f, alpha, n_sub))
        pallas = chain(lambda f: diffuse_pallas(f, alpha, n_sub))
        xla_once = jax.jit(lambda f: diffuse_xla(f, alpha, n_sub))
        pallas_once = jax.jit(lambda f: diffuse_pallas(f, alpha, n_sub))

        row = {
            "size": n,
            "n_substeps": n_sub,
            "fits_vmem": bool(_fits_vmem(fields)),
        }
        t_xla = time_fn(xla, fields)
        row["xla_ms"] = round(t_xla * 1e3, 4)
        best = ("xla", t_xla)
        if row["fits_vmem"]:
            t_pallas = time_fn(pallas, fields)
            row["pallas_ms"] = round(t_pallas * 1e3, 4)
            # on-device numerics: identical stencil, identical order
            np.testing.assert_allclose(
                np.asarray(pallas_once(fields)),
                np.asarray(xla_once(fields)),
                rtol=1e-6,
                atol=1e-6,
            )
            row["numerics_match"] = True
            if t_pallas < best[1]:
                best = ("pallas", t_pallas)
            row["speedup_pallas_over_xla"] = round(t_xla / t_pallas, 3)
        # beyond-VMEM contender: halo-overlap row tiling
        if _tile_rows(n, n, n_sub, 4) is not None and n_sub + 8 <= n:
            tiled = chain(lambda f: diffuse_pallas_tiled(f, alpha, n_sub))
            tiled_once = jax.jit(
                lambda f: diffuse_pallas_tiled(f, alpha, n_sub)
            )
            t_tiled = time_fn(tiled, fields)
            row["pallas_tiled_ms"] = round(t_tiled * 1e3, 4)
            np.testing.assert_allclose(
                np.asarray(tiled_once(fields)),
                np.asarray(xla_once(fields)),
                rtol=1e-6,
                atol=1e-6,
            )
            row["tiled_numerics_match"] = True
            if t_tiled < best[1]:
                best = ("pallas_tiled", t_tiled)
            row["speedup_tiled_over_xla"] = round(t_xla / t_tiled, 3)
        row["winner"] = best[0]
        report["results"].append(row)
        print(json.dumps(row), flush=True)

    # -- the decisive comparison: the stencil IN CONTEXT ---------------------
    # A lone stencil chain is perfectly fused by XLA, but inside the full
    # colony step program the substep scan spills to HBM — so the auto
    # policy is decided by the config-2 window throughput, not the
    # isolated kernel times above.
    from lens_tpu.models.composites import ecoli_lattice

    in_context = {}
    for impl in ("pallas", "xla", "adi"):
        n_agents = 10240
        spatial, _ = ecoli_lattice({"capacity": n_agents})
        spatial.lattice.impl = impl
        state = spatial.initial_state(n_agents, jax.random.PRNGKey(0))
        window = jax.jit(
            lambda s: spatial.run(s, 32.0, 1.0, emit_every=32)[0]
        )
        state = jax.block_until_ready(window(state))
        t0 = time.perf_counter()
        jax.block_until_ready(window(state))
        dt = time.perf_counter() - t0
        in_context[impl] = round(n_agents * 32.0 / dt, 1)
        print(json.dumps({"in_context_config2": impl, "agent_steps_per_sec": in_context[impl]}), flush=True)
    report["in_context_config2_agent_steps_per_sec"] = in_context
    winner = max(in_context, key=in_context.get)
    report["in_context_winner"] = winner
    report["auto_decision"] = (
        f"measured in-context winner: {winner}. `auto` currently routes "
        f"pallas-when-fits-VMEM / xla otherwise (adi is opt-in via "
        f"lattice impl='adi'); promote the winner to `auto` only with "
        f"this record as evidence."
    )

    with open("BENCH_DIFFUSION_AB.json", "w") as f:
        json.dump(report, f, indent=2)


if __name__ == "__main__":
    main()
