"""MFU / roofline accounting for the flagship configs (VERDICT r4 item 3).

agent-steps/s says nothing about whether the chip is BUSY; with no
published reference numbers (BASELINE `published: {}`), utilization is
the only honest yardstick. Per flagship config this bench records:

- an analytic FLOPs-per-step model (diffusion stencil substeps, LP
  factorization + solves at the MEASURED mean iteration count from the
  state's lp_iterations telemetry, tau-leap expression, per-agent
  kinetics) — the model the MFU numbers use;
- XLA's compiled cost analysis as a cross-check, labeled for what it is:
  `scan`/`while` bodies are counted ONCE, so it is a lower bound that
  undercounts by roughly the loop trip counts (measured ~70x on the LP
  window) — useful only to sanity-check the model's single-iteration
  magnitude;
- measured window wall-clock -> achieved FLOP/s -> MFU against the
  device's dense bf16 peak (conservative: the LP/exchange math is
  f32-pinned and cannot reach bf16 peak, so true utilization is higher);
- model bytes-touched -> arithmetic intensity, which names the roofline
  side (HBM-bound vs compute-bound). The per-op idle breakdown still
  needs an on-device `--trace` capture (queued with the TPU work).

Writes BENCH_MFU.json and prints one JSON line per config.
"""

import json
import time

import numpy as np

from bench_lp_sizes import lp_flops
from lens_tpu.utils.platform import guard_accelerator_or_exit

#: Dense peak FLOP/s by device kind (bf16 for TPUs; host CPUs record no
#: MFU — there is no meaningful single peak for this box).
PEAK_FLOPS = {
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v4": 275e12,
    "TPU v6e": 918e12,
}

WINDOW_S = 32.0          # TPU window; CPU runs shrink it (see main)

#: per-agent-per-step FLOPs of one FULL colony step for the config-2
#: composite (biology + division bookkeeping). XLA-DERIVED (VERDICT r4
#: task 7): jit(colony.step).lower(...).compile().cost_analysis() on the
#: isolated single step — no scan, so the counter is exact — measured
#: 540.3 at n=1024 (biology alone: 288). The old hand model (150) was a
#: 3.6x undercount. Re-derive with `python bench_mfu.py --validate`.
KINETIC_FLOPS = 540.0
#: per-gene-per-step FLOPs of the tau-leap expression block. XLA-DERIVED
#: the same way (difference of the 3b biology step with and without the
#: expression process): 3959.6 per gene under the HYBRID Poisson sampler
#: (ops.sampling, the round-6 default). Counter caveat discovered while
#: re-deriving: tau_leap_window scans its substeps INTERNALLY, so even
#: the "isolated step" counts the substep body once (not x substeps) —
#: and the two samplers sit on opposite sides of that counter. The
#: hybrid's fixed-trip inversion is an unrolled loop (fully counted)
#: plus a bulk uniform block OUTSIDE the scan (fully counted); the old
#: exact sampler's rejection loops were lax.while bodies (counted
#: ONCE). That is why this constant ROSE from the round-5 value (3016,
#: exact sampler) while the measured expression wall-clock dropped
#: ~8.5x (BENCH_PHASES_CPU_r06.json): the constant follows XLA's
#: counted-once convention, the bench records follow the wall clock.
#: Re-derive with `python bench_mfu.py --validate`.
GENE_FLOPS = 3960.0


def _stencil_flops(lattice, steps):
    h, w = lattice.shape
    m = len(lattice.molecules)
    # 5-point FTCS: 4 adds + 2 muls per cell per substep per molecule
    return steps * lattice.n_substeps * m * h * w * 6.0


def _flagships(window_s):
    import jax

    from lens_tpu.models.composites import ecoli_lattice, rfba_lattice

    out = {}

    def window(spatial):
        return lambda s: spatial.run(
            s, window_s, 1.0, emit_every=int(window_s)
        )[0]

    n2 = 10240
    spatial2, _ = ecoli_lattice({"capacity": n2})

    def model2(state):
        return _stencil_flops(spatial2.lattice, window_s) + (
            window_s * n2 * KINETIC_FLOPS
        )

    out["2"] = (n2, spatial2, window(spatial2), model2)

    for name, net in (("3b", "ecoli_core"), ("3c", "ecoli_core_full")):
        n3 = 1024
        spatial3, _ = rfba_lattice(
            {
                "capacity": n3,
                "shape": (64, 64),
                "metabolism": {"network": net},
                "expression": {"genes": net},
            }
        )
        procs = spatial3.colony.compartment.processes
        proc = procs["metabolism"]
        genes = len(procs["expression"].genes)
        m_rows = len(proc.internal)
        n_cols = proc._n_lp_vars

        def model3(state, spatial3=spatial3, n3=n3, m_rows=m_rows,
                   n_cols=n_cols, genes=genes):
            iters = float(
                np.asarray(
                    state.colony.agents["fluxes"]["lp_iterations"]
                ).mean()
            )
            return (
                _stencil_flops(spatial3.lattice, window_s)
                + window_s * n3 * lp_flops(m_rows, n_cols, iters)
                + window_s * n3 * genes * GENE_FLOPS
                + window_s * n3 * KINETIC_FLOPS
            )

        out[name] = (n3, spatial3, window(spatial3), model3)
    return out


def _xla_cost(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    return ca or {}


def validate_constants():
    """Re-derive KINETIC_FLOPS / GENE_FLOPS from XLA's compiled cost
    analysis of the ISOLATED single step — the one place the counter is
    exact (no scan/while, so nothing is counted once that runs N times).
    Prints one JSON line per constant with the model-vs-XLA ratio; the
    constants above are frozen from this measurement (2026-07-31, CPU
    backend — FLOP counts are backend-independent op math).

    Reconciliation of the whole-window undercount: the diffusion
    substeps run under lax.scan, so the window's XLA count includes the
    stencil body ONCE (measured: spatial step 1.10e6 vs 27-substep model
    1.06e7 — the x27 trip count is exactly the gap); the LP while-loop
    body is likewise counted once (x~iterations). That is why the
    whole-window `xla_flops_lower_bound` sits ~70x under the analytic
    model and why these single-step isolations are the honest
    cross-check.
    """
    import jax

    def xla_flops(fn, *args):
        return float(
            _xla_cost(jax.jit(fn).lower(*args).compile()).get("flops", 0.0)
        )

    from lens_tpu.models.composites import ecoli_lattice, rfba_lattice

    n = 1024
    spatial, _ = ecoli_lattice({"capacity": n})
    cs = spatial.colony.initial_state(n, key=jax.random.PRNGKey(0))
    kinetic = xla_flops(lambda c: spatial.colony.step(c, 1.0), cs) / n
    print(json.dumps({
        "constant": "KINETIC_FLOPS", "frozen": KINETIC_FLOPS,
        "xla_measured": round(kinetic, 1),
        "ratio": round(KINETIC_FLOPS / kinetic, 3),
    }))

    def biology_flops(expression):
        sp, _ = rfba_lattice({
            "capacity": 256, "shape": (64, 64),
            "metabolism": {"network": "ecoli_core"},
            "expression": expression,
        })
        c = sp.colony.initial_state(256, key=jax.random.PRNGKey(0))
        return (
            xla_flops(lambda s: sp.colony.step_biology(s, 1.0), c),
            sp.colony.compartment.processes,
        )

    with_expr, procs = biology_flops({"genes": "ecoli_core"})
    without, _ = biology_flops(None)
    genes = len(procs["expression"].genes)
    per_gene = (with_expr - without) / 256 / genes
    print(json.dumps({
        "constant": "GENE_FLOPS", "frozen": GENE_FLOPS,
        "xla_measured": round(per_gene, 1), "genes": genes,
        "ratio": round(GENE_FLOPS / per_gene, 3),
    }))
    ok = (
        0.5 <= KINETIC_FLOPS / kinetic <= 2.0
        and 0.5 <= GENE_FLOPS / per_gene <= 2.0
    )
    print(json.dumps({"constants_within_2x_of_xla": ok}))
    return ok


def main():
    guard_accelerator_or_exit()
    import jax

    backend = jax.default_backend()
    kind = jax.devices()[0].device_kind
    peak = next(
        (v for k, v in PEAK_FLOPS.items() if k.lower() in kind.lower()), None
    )
    # the full-network 3c window alone takes >30 min on this 1-core host;
    # the CPU record shrinks the window (recorded per row) — TPU runs the
    # full 32 s
    window_s = WINDOW_S if backend != "cpu" else 8.0
    rows = []
    for name, (n, spatial, window_fn, model) in _flagships(window_s).items():
        state = spatial.initial_state(n, jax.random.PRNGKey(0))
        window = jax.jit(window_fn)
        compiled = window.lower(state).compile()
        ca = _xla_cost(compiled)
        state = jax.block_until_ready(window(state))  # warm-up
        t0 = time.perf_counter()
        state = jax.block_until_ready(window(state))
        dt = time.perf_counter() - t0
        flops = float(model(state))
        row = {
            "config": name,
            "agents": n,
            "window_s": window_s,
            "agent_steps_per_s": n * window_s / dt,
            "model_flops_per_window": flops,
            "model_flops_per_agent_step": flops / (n * window_s),
            "achieved_flops_per_s": flops / dt,
            "mfu": flops / dt / peak if peak else None,
            "xla_flops_lower_bound": float(ca.get("flops", 0.0)) or None,
            "xla_bytes_lower_bound": (
                float(ca.get("bytes accessed", 0.0)) or None
            ),
            "device_kind": kind,
        }
        rows.append(row)
        print(json.dumps({
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in row.items()
        }))
    out = {
        "backend": backend,
        "device_kind": kind,
        "peak_flops_assumed": peak,
        "note": (
            "MFU = analytic-model FLOPs / wall / dense-bf16 peak "
            "(conservative: f32-pinned math cannot reach bf16 peak). "
            "xla_*_lower_bound come from compiled.cost_analysis(), which "
            "counts scan/while bodies ONCE — lower bounds only. Per-op "
            "idle breakdown needs an on-device --trace capture."
        ),
        "rows": rows,
    }
    with open("BENCH_MFU.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    import sys

    if "--validate" in sys.argv:
        guard_accelerator_or_exit()
        raise SystemExit(0 if validate_constants() else 1)
    main()
